"""Async overlapped collectives: work handles + the per-group runner.

The synchronous coalesced path (PR 4) serializes three stages that have
no data dependency across buckets: materialize the gradients on the host
(device->host copy), reduce them over the shm/ring transport, and hand
the results back. This module pipelines them — the shape the
concurrency-limits study (arXiv:2011.03641) and the MLPerf TPU-v3
scaling report (arXiv:1909.09756) both identify as the remaining win
once the device plane is fast:

  * ``allreduce_coalesced_async(...) -> CollectiveWork`` returns
    immediately; the caller's thread goes straight back to dispatching
    device compute while the group's runner does the gradient movement.
  * The runner is TWO persistent daemon threads per group. The *mover*
    materializes one BUCKET at a time (one batched ``jax.device_get``
    per bucket, never one per leaf) and packs it into a pooled staging
    buffer; the *reducer* runs the transport rounds. A bounded handoff
    queue between them means bucket i's ring reduce-scatter streams
    while bucket i+1's gradients are still leaving the device.
  * Buckets materialize in REVERSE flatten order: backprop produces the
    last layers' gradients first, so the first bucket the reducer sees
    is the one whose bytes are ready earliest.
  * Staging buffers come from a persistent pool keyed by (dtype, size)
    — a steady-state training step re-acquires the same buffers and
    allocates nothing (``ray_tpu_collective_staging_bytes`` goes flat
    after warmup), and a MEAN is pre-scaled into the pack copy so no
    post-reduce divide pass exists anywhere.
  * ``on_bucket(indices, arrays)`` (optional) fires on the reducer
    thread the moment each bucket's reduce lands — the hook the
    pipeline trainer's fused in-bucket optimizer rides, so a bucket's
    jitted apply overlaps the remaining buckets' rounds. The sync
    fallback still fires it once per bucket on the caller's thread; a
    callback exception poisons the group like any mid-round failure.

Failure semantics match the synchronous path exactly: ANY exception
escaping a round poisons the group (a retried collective could otherwise
consume a stale transport round as fresh data), the failing handle gets
the real error, and every queued handle fails with a clean
``CollectiveError`` — never a hang, never a silently wrong sum.
``destroy()`` with work in flight fails all pending handles first, then
tears down the transport (closing channels/inboxes, which also unblocks
a reducer parked mid-round), so the group's pins unwind through the same
paths the sync collectives use.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import flight
from ray_tpu.util.collective import _metrics
from ray_tpu.util.collective.types import (CollectiveError, ReduceOp,
                                           prescale_factor)

logger = logging.getLogger(__name__)

_STOP = object()

# flight-recorder span ids: one span per mover/reducer bucket round shows
# the overlap (or lack of it) the aggregate histograms can only average
_F_MOVE = flight.intern("col.mover_bucket")
_F_REDUCE = flight.intern("col.reduce_bucket")
_F_WAIT = flight.intern("col.wait")


# ----------------------------------------------------------------- handles


class CollectiveWork:
    """Handle for one in-flight ``allreduce_coalesced_async`` call."""

    def __init__(self, group_name: str):
        self._group_name = group_name
        self._event = threading.Event()
        self._result: Optional[List[np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    #: False only on handles returned by the synchronous fallback — lets
    #: benchmarks assert the overlap path actually engaged.
    overlapped = True

    def done(self) -> bool:
        """True once the result (or the failure) is available."""
        return self._event.is_set()

    def wait(self, timeout_ms: Optional[int] = None) -> List[np.ndarray]:
        """Block for the reduced arrays (input order). Raises the round's
        error if the work failed. The blocked span is recorded in
        ``ray_tpu_collective_wait_seconds`` — against
        ``round_seconds`` it gives the overlap fraction."""
        t0 = time.perf_counter()
        t0f = flight.now()
        ok = self._event.wait(
            None if timeout_ms is None else timeout_ms / 1000.0)
        flight.span_since(_F_WAIT, t0f)
        _metrics.wait_seconds.observe(time.perf_counter() - t0)
        if not ok:
            raise TimeoutError(
                f"collective group {self._group_name!r}: async work not "
                f"done within {timeout_ms} ms")
        if self._exc is not None:
            raise self._exc
        return self._result  # type: ignore[return-value]

    def exception(self) -> Optional[BaseException]:
        """The failure, if the work is done and failed (None otherwise)."""
        return self._exc if self._event.is_set() else None

    # -- runner side (first finish/fail wins; late poison fan-out is a no-op)

    def _finish(self, result: List[np.ndarray]) -> None:
        if not self._event.is_set():
            self._result = result
            self._event.set()

    def _fail(self, exc: BaseException) -> None:
        if not self._event.is_set():
            self._exc = exc
            self._event.set()


class _CompletedWork(CollectiveWork):
    """Synchronous-fallback handle: already done at construction."""

    overlapped = False

    def __init__(self, group_name: str, result: List[np.ndarray]):
        super().__init__(group_name)
        self._finish(result)


# ------------------------------------------------------------ staging pool


class StagingPool:
    """Persistent flat staging buffers keyed by (dtype, elements).

    A training step's bucket layout is a pure function of its gradient
    tree, so after one warmup step every ``acquire`` is a pool hit: the
    allocs counter stops moving and the bytes gauge goes flat — the
    zero-new-allocations proof the overlap acceptance bar asks for."""

    def __init__(self):
        self._free: Dict[Tuple[str, int], List[np.ndarray]] = {}
        self._lock = threading.Lock()
        self._closed = False

    def acquire(self, dtype: np.dtype, nelems: int) -> np.ndarray:
        key = (np.dtype(dtype).str, int(nelems))
        with self._lock:
            bufs = self._free.get(key)
            if bufs:
                return bufs.pop()
        buf = np.empty(nelems, np.dtype(dtype))
        _metrics.staging_allocs_total.inc()
        _metrics.staging_bytes.inc(buf.nbytes)
        return buf

    def release(self, buf: np.ndarray) -> None:
        with self._lock:
            if self._closed:
                # a buffer in flight when drain() ran: drop it (nothing
                # will ever acquire from a drained pool) and settle its
                # share of the gauge so bytes return to baseline
                _metrics.staging_bytes.dec(buf.nbytes)
                return
            self._free.setdefault((buf.dtype.str, buf.size), []).append(buf)

    def drain(self) -> None:
        """Drop every pooled buffer (group destroy); buffers still in
        flight settle through ``release`` above."""
        with self._lock:
            self._closed = True
            freed = sum(b.nbytes for bufs in self._free.values()
                        for b in bufs)
            self._free.clear()
        if freed:
            _metrics.staging_bytes.dec(freed)


# ---------------------------------------------------------- bucket layout


def bucket_layout(arrs: Sequence[Any], bucket_bytes: int) -> List[List[int]]:
    """Greedy adjacent same-dtype buckets bounded by ``bucket_bytes`` —
    the PR-4 coalescing rule, factored out so the sync path and the
    async runner pack identically (works on device arrays too: only
    ``.dtype`` / ``.size`` are touched, never the bytes)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_sz = 0
    for i, a in enumerate(arrs):
        dt = np.dtype(a.dtype)
        nbytes = int(a.size) * dt.itemsize
        if cur and (dt != np.dtype(arrs[cur[0]].dtype)
                    or cur_sz + nbytes > bucket_bytes):
            buckets.append(cur)
            cur = []
            cur_sz = 0
        cur.append(i)
        cur_sz += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def validate_on_bucket(on_bucket) -> None:
    """Fail a bad ``on_bucket=`` LOUDLY on the caller's thread, at
    construction: inside the runner a non-callable would poison the
    whole group on the first bucket (and a falsy-but-wrong value — 0,
    "", an awaited coroutine — would silently mean "no callback", the
    falsy-zero class of bug)."""
    if on_bucket is None or callable(on_bucket):
        return
    raise TypeError(
        f"on_bucket must be a callable (indices, arrays) -> None, got "
        f"{type(on_bucket).__name__}: {on_bucket!r}")


def fire_on_bucket(leaves: Sequence[Any], bucket_bytes: int,
                   results: Sequence[np.ndarray], on_bucket) -> None:
    """Replay the runner's per-bucket callback contract over already-
    reduced ``results``: same-dtype buckets laid out from the INPUT
    leaves (an integer MEAN widens its results to float, which would
    regroup), fired in the runner's reverse-flatten order, each leaf
    exactly once. The ONE encoding of the contract every synchronous
    fallback (BaseGroup, solo GradientAverager) replays — bucket_layout
    only touches .dtype/.size, so device-array leaves cost no
    materialization here."""
    for bucket in reversed(bucket_layout(leaves, bucket_bytes)):
        on_bucket(list(bucket), [results[i] for i in bucket])


def validate_out(leaves: Sequence[Any], op: ReduceOp,
                 out: Optional[Sequence[np.ndarray]],
                 world_size: int) -> None:
    """Fail bad ``out=`` combinations LOUDLY on the caller's thread —
    inside the runner they would poison the whole group (and a shape
    slip could silently land bytes in a detached reshape copy)."""
    if out is None:
        return
    if len(out) != len(leaves):
        raise ValueError(
            f"out has {len(out)} arrays for {len(leaves)} tensors")
    if op is ReduceOp.MEAN and any(
            prescale_factor(op, a.dtype, world_size) is None
            for a in leaves):  # per leaf — buckets split by dtype, so one
        # integer leaf anywhere would widen ITS bucket and fail its copyto
        raise ValueError(
            "op='mean' over integer tensors widens to float — it cannot "
            "land in integer out= buffers; drop out= or cast the inputs")
    for i, (a, o) in enumerate(zip(leaves, out)):
        if tuple(o.shape) != tuple(a.shape) or \
                np.dtype(o.dtype) != np.dtype(a.dtype):
            raise ValueError(
                f"out[{i}] is {np.dtype(o.dtype)}{tuple(o.shape)} but "
                f"tensor {i} is {np.dtype(a.dtype)}{tuple(a.shape)} — "
                f"out= buffers must match the inputs exactly")


def _materialize(leaves: List[Any]) -> List[np.ndarray]:
    """One batched device->host transfer for a whole bucket (the per-leaf
    ``np.asarray`` loop this replaces serialized one copy per tensor)."""
    if all(isinstance(x, np.ndarray) for x in leaves):
        return leaves  # host-side already; nothing to move
    import jax

    return [np.asarray(x) for x in jax.device_get(list(leaves))]


# ----------------------------------------------------------------- runner


class _Submission:
    __slots__ = ("work", "leaves", "op", "timeout_ms", "bucket_bytes",
                 "out", "results", "remaining", "on_bucket")

    def __init__(self, work: CollectiveWork, leaves: List[Any],
                 op: ReduceOp, timeout_ms: int, bucket_bytes: int,
                 out: Optional[Sequence[np.ndarray]],
                 on_bucket=None):
        self.work = work
        self.leaves = leaves
        self.op = op
        self.timeout_ms = timeout_ms
        self.bucket_bytes = bucket_bytes
        self.out = out
        self.on_bucket = on_bucket  # per-bucket completion callback
        self.results: List[Optional[np.ndarray]] = [None] * len(leaves)
        self.remaining = 0  # buckets still to reduce (set by the mover)


class _BucketTask:
    __slots__ = ("sub", "staging", "meta", "scale")

    def __init__(self, sub: _Submission, staging: np.ndarray,
                 meta: List[Tuple[int, tuple, int]], scale: Optional[float]):
        self.sub = sub
        self.staging = staging
        self.meta = meta  # (leaf index, shape, elements) per packed leaf
        self.scale = scale  # non-None: MEAN pre-scaled into the pack copy


class AsyncRunner:
    """Per-group two-stage pipeline executing async collective work.

    Submissions run strictly in submission order and buckets within a
    submission in reverse flatten order — deterministic, so every rank's
    transport sees the identical op sequence (the standard collective
    ordering requirement) as long as ranks submit in the same order,
    exactly as they must for the sync API."""

    def __init__(self, group):
        self._group = group  # HostGroup
        try:
            from ray_tpu._private.api import _require_core

            depth = max(1, int(
                _require_core().config.collective_overlap_depth))
        except Exception:
            depth = 2
        self.pool = StagingPool()
        self._subq: "queue.Queue" = queue.Queue()
        self._bucketq: "queue.Queue" = queue.Queue(maxsize=depth)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._pending: List[_Submission] = []
        self._dead: Optional[str] = None
        name = group.group_name
        self._mover = threading.Thread(
            target=self._mover_loop, daemon=True, name=f"col-mover-{name}")
        self._reducer = threading.Thread(
            target=self._reducer_loop, daemon=True,
            name=f"col-reduce-{name}")
        self._mover.start()
        self._reducer.start()

    # ------------------------------------------------------------- public

    def submit(self, tensors: Sequence[Any], op: ReduceOp, timeout_ms: int,
               bucket_bytes: int,
               out: Optional[Sequence[np.ndarray]],
               on_bucket=None) -> CollectiveWork:
        validate_on_bucket(on_bucket)
        work = CollectiveWork(self._group._public_name)
        if not len(tensors):
            work._finish([])
            return work
        leaves = [t if hasattr(t, "dtype") and hasattr(t, "size")
                  else np.asarray(t) for t in tensors]
        validate_out(leaves, op, out, self._group.world_size)
        sub = _Submission(work, leaves, op, timeout_ms, bucket_bytes, out,
                          on_bucket=on_bucket)
        with self._lock:
            if self._dead is not None:
                raise CollectiveError(
                    f"collective group {self._group._public_name!r} is "
                    f"poisoned by an earlier failure ({self._dead}); "
                    f"destroy and re-create the group")
            self._pending.append(sub)
        self._subq.put(sub)
        return work

    def flush(self, timeout_s: float) -> None:
        """Block until no async work is in flight (sync ops interleave
        AFTER the queue drains, so the transport op order stays identical
        on every rank). A poisoned runner returns immediately — the sync
        caller then hits the group's poison check."""
        deadline = time.monotonic() + timeout_s
        with self._idle:
            while self._pending and self._dead is None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"collective group {self._group._public_name!r}: "
                        f"sync collective blocked {timeout_s:.1f}s behind "
                        f"unfinished async work")
                self._idle.wait(min(left, 0.5))

    def shutdown(self, reason: str = "group destroyed") -> None:
        """Fail every unfinished handle NOW and stop the threads. The
        caller destroys the transport right after — which is what
        unblocks a reducer parked mid-round, so its error lands on an
        already-failed handle (idempotent)."""
        self._fail_pending(CollectiveError(
            f"collective group {self._group._public_name!r}: {reason} "
            f"with collective work in flight"), mark_dead=reason)
        self._subq.put(_STOP)
        self.pool.drain()

    # ----------------------------------------------------------- internals

    def _fail_pending(self, exc: BaseException, mark_dead: str) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = mark_dead
            pending, self._pending = self._pending, []
            self._idle.notify_all()
        for sub in pending:
            sub.work._fail(exc)

    def _poison(self, exc: BaseException) -> None:
        """A round failed: poison the GROUP (same invariant as the sync
        ``_delegate`` path — transport state may be out of step with
        peers) and fail every handle."""
        detail = f"{type(exc).__name__}: {exc}"
        self._group._poisoned = detail
        self._fail_pending(
            exc if isinstance(exc, (CollectiveError, TimeoutError))
            else CollectiveError(detail),
            mark_dead=detail)

    def _finish_bucket(self, sub: _Submission) -> None:
        sub.remaining -= 1
        if sub.remaining == 0:
            with self._lock:
                if sub in self._pending:
                    self._pending.remove(sub)
                self._idle.notify_all()
            sub.work._finish(sub.results)  # type: ignore[arg-type]

    def _mover_loop(self) -> None:
        while True:
            sub = self._subq.get()
            if sub is _STOP:
                self._bucketq.put(_STOP)
                return
            if self._dead is not None:
                continue  # already failed by poison/shutdown fan-out
            try:
                buckets = bucket_layout(sub.leaves, sub.bucket_bytes)
                sub.remaining = len(buckets)
                # reverse-backward: the LAST flattened leaves (deepest
                # layers, first gradients out of backprop) feed the first
                # reduce round, so the reducer never waits on bytes the
                # device hasn't produced yet
                for bucket in reversed(buckets):
                    if self._dead is not None:
                        break
                    t0 = flight.now()
                    host = _materialize([sub.leaves[i] for i in bucket])
                    dtype = host[0].dtype
                    total = sum(a.size for a in host)
                    scale = prescale_factor(
                        sub.op, dtype, self._group.world_size)
                    staging = self.pool.acquire(dtype, total)
                    off = 0
                    meta: List[Tuple[int, tuple, int]] = []
                    for i, a in zip(bucket, host):
                        flat = np.ascontiguousarray(a).reshape(-1)
                        seg = staging[off:off + a.size]
                        if scale is None:
                            seg[...] = flat
                        else:
                            np.multiply(flat, scale, out=seg)
                        meta.append((i, tuple(a.shape), int(a.size)))
                        off += a.size
                    self._bucketq.put(
                        _BucketTask(sub, staging, meta, scale))
                    # includes the handoff-queue wait: a full queue IS
                    # the mover stalling behind the reducer
                    flight.span_since(_F_MOVE, t0)
            except BaseException as e:  # noqa: BLE001 — fail loud + clean
                logger.debug("collective mover failed", exc_info=True)
                self._poison(e)

    def _reducer_loop(self) -> None:
        while True:
            task = self._bucketq.get()
            if task is _STOP:
                return
            if self._dead is not None:
                self.pool.release(task.staging)
                continue  # drain mode: unblock the mover, drop the work
            sub = task.sub
            try:
                t0 = flight.now()
                impl = self._group._impl_for(sub.timeout_ms)
                # MEAN was either pre-scaled into the pack (float dtypes)
                # or falls back to SUM + one divide at unpack — the
                # transport only ever runs an in-place SUM-family round
                op = ReduceOp.SUM if sub.op is ReduceOp.MEAN else sub.op
                red = np.asarray(impl.allreduce(
                    task.staging, op, sub.timeout_ms, out=task.staging))
                _metrics.overlap_rounds_total.inc(
                    labels=_metrics.labels(impl.algo))
                if sub.op is ReduceOp.MEAN and task.scale is None:
                    red = red / self._group.world_size  # integer mean
                off = 0
                for i, shape, size in task.meta:
                    seg = red[off:off + size]
                    if sub.out is not None:
                        # copyto(dst, view-of-seg): correct for ANY dst
                        # layout — dst.reshape(-1) on a non-contiguous
                        # array would write into a detached copy
                        np.copyto(sub.out[i], seg.reshape(shape))
                        sub.results[i] = sub.out[i]
                    else:
                        sub.results[i] = seg.reshape(shape).copy()
                    off += size
                if sub.on_bucket is not None:
                    # per-bucket completion callback, ON THIS THREAD —
                    # the caller's per-bucket work (e.g. a jitted
                    # optimizer apply) overlaps the remaining buckets'
                    # device_get + reduce rounds. Runs BEFORE the
                    # staging release: a raise falls to the handler
                    # below, which releases once and poisons the group
                    # (callback state may be mid-update — same invariant
                    # as a failed round)
                    sub.on_bucket(
                        [i for i, _, _ in task.meta],
                        [sub.results[i] for i, _, _ in task.meta])
                self.pool.release(task.staging)
                flight.span_since(_F_REDUCE, t0)
                self._finish_bucket(sub)
            except BaseException as e:  # noqa: BLE001 — fail loud + clean
                logger.debug("collective reducer failed", exc_info=True)
                self.pool.release(task.staging)
                self._poison(e)
