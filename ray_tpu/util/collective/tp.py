"""Tensor-parallel reduce operators for sharded jitted programs.

Megatron-style tensor parallelism (arXiv:1909.09756) threads two
conjugate operators through each sharded block:

  ``g`` — partial-sum allreduce in the FORWARD pass, identity in the
  backward. Placed on every row-parallel output (attention proj,
  ffn-down) so each rank's partial sum over its local heads / ffn
  columns becomes the full activation.

  ``f`` — identity in the forward, partial-sum allreduce in the
  BACKWARD. Placed on every column-parallel INPUT (the norm outputs
  feeding QKV / ffn-up) so the cotangent flowing back onto the
  replicated residual stream / norm params is the full cross-rank sum.

With this placement replicated params (norms, biases added after ``g``,
embeddings, lm_head) receive exact replicated gradients with no extra
flush-time sync, and sharded params receive exactly their local shard's
gradient.

Two constructions are provided:

``make_tp_reduce_ops(reduce_cb)`` builds the pair over a HOST reducer
(typically ``collective.allreduce`` on a per-(stage, dp-rank) tp group)
via ``jax.pure_callback`` + ``jax.custom_vjp`` — the cross-process form
the pipeline trainer uses. Every rank of a tp group must execute the
same deterministic sequence of ``g``/``f`` applications (the callbacks
carry no op tags — order IS the match), which is why the trainer runs a
static schedule when tp > 1. NOTE this jaxlib's CPU callback executor is
single-threaded and deadlocks above a few-hundred-KB payload per
callback (see microbenchmark._probe_sleep_op) — per-reduce activations
must stay modest on the CPU rig.

``psum_tp_ops(axis_name)`` builds the pair for a SINGLE-TRACE emulation
under ``jax.vmap(..., axis_name=...)`` over a stacked rank axis:
``g = lax.psum``, ``f = identity``. Pass replicated leaves unbatched
(``in_axes=None``) and vmap's broadcast-transpose supplies ``f``'s
backward sum automatically — the clusterless parity oracle the tests
compare against.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import numpy as np


class TpOps(NamedTuple):
    """The conjugate (g, f) pair; both are jax-traceable unary fns."""

    g: Callable  # reduce fwd / identity bwd (row-parallel outputs)
    f: Callable  # identity fwd / reduce bwd (column-parallel inputs)


def make_tp_reduce_ops(reduce_cb: Callable[[np.ndarray], np.ndarray]) -> TpOps:
    """(g, f) over a host partial-sum reducer, usable inside jit.

    ``reduce_cb(arr) -> arr`` must be the tp-group allreduce (SUM); it is
    invoked from jax's host-callback executor thread, once per ``g``
    forward / ``f`` backward application, in program order.
    """
    import jax

    def _reduce(x):
        def _host(a):
            a = np.asarray(a)
            return np.asarray(reduce_cb(a), dtype=a.dtype).reshape(a.shape)

        return jax.pure_callback(
            _host, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    @jax.custom_vjp
    def g(x):
        return _reduce(x)

    g.defvjp(lambda x: (_reduce(x), None), lambda _, ct: (ct,))

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda _, ct: (_reduce(ct),))

    return TpOps(g=g, f=f)


def psum_tp_ops(axis_name: str = "tp") -> TpOps:
    """(g, f) for single-trace emulation under vmap over the rank axis."""
    import jax

    return TpOps(g=lambda x: jax.lax.psum(x, axis_name), f=lambda x: x)
