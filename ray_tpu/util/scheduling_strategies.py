"""Public scheduling-strategy surface (≈ `ray.util.scheduling_strategies`:
NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy + the
In/NotIn/Exists/DoesNotExist label operators)."""

from ray_tpu._private.task_spec import (  # noqa: F401
    DoesNotExist,
    Exists,
    In,
    NodeAffinityStrategy,
    NodeLabelStrategy,
    NotIn,
    PlacementGroupStrategy,
    RandomStrategy,
    SchedulingStrategy,
    SpreadStrategy,
)

# reference-compatible aliases
NodeAffinitySchedulingStrategy = NodeAffinityStrategy
NodeLabelSchedulingStrategy = NodeLabelStrategy
PlacementGroupSchedulingStrategy = PlacementGroupStrategy
