"""Distributed FIFO queue backed by an actor.

Analog of `ray.util.queue.Queue` (`python/ray/util/queue.py`): an async
actor owns an `asyncio.Queue`; any process holding the handle can
put/get with optional blocking + timeout. Empty/Full mirror the
reference's exception surface (aliases of the stdlib queue exceptions).
"""

from __future__ import annotations

import asyncio
from queue import Empty, Full  # re-exported, reference-compatible
from typing import Any, List, Optional

import ray_tpu

__all__ = ["Queue", "Empty", "Full"]


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._maxsize = maxsize

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        if timeout is None:
            await self._q.put(item)
            return True
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item: Any) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def put_nowait_batch(self, items: List[Any]) -> int:
        n = 0
        for it in items:
            try:
                self._q.put_nowait(it)
                n += 1
            except asyncio.QueueFull:
                break
        return n

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return (True, await self._q.get())
        try:
            return (True, await asyncio.wait_for(self._q.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    async def get_nowait(self):
        try:
            return (True, self._q.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    async def get_nowait_batch(self, max_items: int) -> List[Any]:
        out = []
        while len(out) < max_items:
            try:
                out.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    """Sync client facade; safe to pass between tasks/actors (pickles to
    the underlying actor handle)."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict]
                 = None, _actor=None):
        if _actor is not None:
            self._actor = _actor
            return
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        # a parked blocking get() must not hold the actor's only execution
        # slot — puts have to interleave to wake it
        opts.setdefault("max_concurrency", 1000)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self._actor.put_nowait.remote(item)):
                raise Full
            return
        ok = ray_tpu.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> int:
        return ray_tpu.get(self._actor.put_nowait_batch.remote(list(items)))

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, max_items: int) -> List[Any]:
        return ray_tpu.get(
            self._actor.get_nowait_batch.remote(int(max_items)))

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self._actor.full.remote())

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass

    @classmethod
    def _from_actor(cls, actor) -> "Queue":
        return cls(_actor=actor)

    def __reduce__(self):
        # pickling must NOT create a fresh queue actor — rebind the handle
        return (Queue._from_actor, (self._actor,))
