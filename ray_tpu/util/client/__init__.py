"""Ray-Client-equivalent: drive a remote cluster from a process that never
joins it (≈ `python/ray/util/client/`).

Usage (either form):

    import ray_tpu
    ray_tpu.init(address="client://head-host:10001")
    # ... ray_tpu.remote / get / put / actors as usual ...

    # or explicitly:
    from ray_tpu.util import client
    ctx = client.connect("head-host:10001")

Server side (on any cluster host):

    python -m ray_tpu.util.client.server --cluster <controller host:port>
"""

from ray_tpu.util.client.client import ClientContext
from ray_tpu.util.client.common import ClientActorHandle, ClientObjectRef
from ray_tpu.util.client.server import ClientServer


def connect(address: str, *, namespace: str = "default") -> ClientContext:
    """Connect the current process to a client server and install the
    context as the module-level API backend."""
    from ray_tpu._private import api

    # reject before building a live context (threads + a server session)
    if api._core is not None:
        raise RuntimeError(
            "cannot enter client mode: this process already runs a driver "
            "(call shutdown() first)")
    ctx = ClientContext(address, namespace=namespace)
    try:
        api._install_client(ctx)
    except BaseException:
        ctx.disconnect()
        raise
    return ctx


def disconnect() -> None:
    from ray_tpu._private import api

    api._uninstall_client()


__all__ = [
    "ClientActorHandle",
    "ClientContext",
    "ClientObjectRef",
    "ClientServer",
    "connect",
    "disconnect",
]
