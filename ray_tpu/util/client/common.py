"""Client-side stand-ins for ObjectRef/ActorHandle plus the persistent-id
pickle bridge used on both ends of the client protocol.

TPU-native analog of the reference's Ray Client data layer
(`python/ray/util/client/common.py` ClientObjectRef/ClientActorHandle): refs
and handles cross the wire as persistent ids, so they survive arbitrary
nesting (containers, closures) without a deep-walk of the payload.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, Optional

import cloudpickle


class ClientObjectRef:
    """A driver-side stub for an object living in the remote cluster.

    Holds only the object-id hex; the paired server session pins the real
    ObjectRef until this stub is garbage-collected (the context batches
    release notifications)."""

    __slots__ = ("_hex", "_ctx", "__weakref__")

    def __init__(self, hex_id: str, ctx=None):
        self._hex = hex_id
        self._ctx = ctx

    def hex(self) -> str:
        return self._hex

    def __repr__(self) -> str:
        return f"ClientObjectRef({self._hex[:16]})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ClientObjectRef) and other._hex == self._hex

    def __hash__(self) -> int:
        return hash(self._hex)

    def future(self):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self._ctx.get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def __del__(self):
        ctx = self._ctx
        if ctx is not None:
            try:
                ctx._release(self._hex)
            except Exception:
                pass


class ClientActorMethod:
    __slots__ = ("_handle", "_name", "_num_returns")

    def __init__(self, handle: "ClientActorHandle", name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1) -> "ClientActorMethod":
        return ClientActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        return self._handle._ctx.actor_call(
            self._handle, self._name, args, kwargs,
            num_returns=self._num_returns)

    def __call__(self, *a, **k):
        raise TypeError(
            f"actor method {self._name}() cannot be called directly; "
            f"use .remote()")


class ClientActorHandle:
    """Driver-side stub for a remote actor; methods proxy through the
    client context."""

    def __init__(self, actor_hex: str, ctx=None, class_name: str = ""):
        self._hex = actor_hex
        self._ctx = ctx
        self._class_name = class_name

    @property
    def _actor_id(self):  # parity helper for code that inspects handles
        from ray_tpu._private.ids import ActorID

        return ActorID.from_hex(self._hex)

    def __getattr__(self, name: str) -> ClientActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ClientActorHandle({self._class_name}, {self._hex[:16]})"


# --------------------------------------------------------------------- pickle

REF_PID = "ref"
ACTOR_PID = "actor"


class _Pickler(cloudpickle.CloudPickler):
    """cloudpickle with a persistent_id hook: `id_for(obj)` returns a
    (kind, hex) tuple for refs/handles, or None to pickle normally."""

    def __init__(self, file, id_for: Callable[[Any], Optional[tuple]]):
        super().__init__(file, protocol=pickle.HIGHEST_PROTOCOL)
        self._id_for = id_for

    def persistent_id(self, obj):
        return self._id_for(obj)


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, load_pid: Callable[[tuple], Any]):
        super().__init__(file)
        self._load_pid = load_pid

    def persistent_load(self, pid):
        return self._load_pid(pid)


def dumps_with_ids(obj: Any, id_for: Callable[[Any], Optional[tuple]]) -> bytes:
    buf = io.BytesIO()
    _Pickler(buf, id_for).dump(obj)
    return buf.getvalue()


def loads_with_ids(blob: bytes, load_pid: Callable[[tuple], Any]) -> Any:
    return _Unpickler(io.BytesIO(blob), load_pid).load()
