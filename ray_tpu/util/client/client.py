"""Client context: drives a remote cluster through a client server.

TPU-native analog of the reference's Ray Client data client
(`python/ray/util/client/dataclient.py` + `worker.py`): a background event
loop owns one RpcClient; the public methods are synchronous and mirror the
driver API surface (`put/get/wait/remote/actor/...`). Installed into
`ray_tpu._private.api` as the module-level backend when
``ray_tpu.init(address="client://host:port")`` is used.
"""

from __future__ import annotations

import asyncio
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.util.client.common import (ACTOR_PID, REF_PID, ClientActorHandle,
                                        ClientObjectRef, dumps_with_ids,
                                        loads_with_ids)


class ClientContext:
    def __init__(self, address: str, *, namespace: str = "default",
                 request_timeout_s: float = 300.0):
        self._address = address
        self._namespace = namespace
        self._session = uuid.uuid4().hex
        self._dead_refs: List[str] = []
        self._dead_lock = threading.Lock()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="client-io", daemon=True)
        self._thread.start()
        try:
            self._client = self._run(self._make_client(request_timeout_s))
            info = self._call("cl_ping", {"namespace": namespace})
        except BaseException:
            # connection failed: don't leak the io thread/loop
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=2)
            raise
        self._server_namespace = info.get("namespace", namespace)

    async def _make_client(self, request_timeout_s):
        from ray_tpu._private.rpc import RpcClient

        return RpcClient(self._address, request_timeout_s=request_timeout_s)

    # ----------------------------------------------------------------- plumbing

    def _run(self, coro, timeout=None):
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop).result(timeout)

    def _call(self, method: str, body: Dict[str, Any],
              timeout: Optional[float] = None) -> Any:
        body = dict(body)
        body["session"] = self._session
        body.setdefault("namespace", self._namespace)
        with self._dead_lock:
            rel, self._dead_refs = self._dead_refs, []
        if rel:
            self._run(self._client.notify(
                "cl_release", {"session": self._session, "refs": rel}))
        reply = self._run(self._client.call(method, body, timeout=timeout))
        if isinstance(reply, dict) and "exc" in reply:
            raise self._loads(reply["exc"])
        if isinstance(reply, dict) and "ok" in reply:
            return self._loads(reply["ok"])
        return reply

    def _release(self, hex_id: str) -> None:
        with self._dead_lock:
            self._dead_refs.append(hex_id)

    def _id_for(self, obj):
        if isinstance(obj, ClientObjectRef):
            return (REF_PID, obj._hex)
        if isinstance(obj, ClientActorHandle):
            return (ACTOR_PID, obj._hex)
        return None

    def _load_pid(self, pid):
        kind, hex_id = pid[0], pid[1]
        if kind == REF_PID:
            return ClientObjectRef(hex_id, self)
        if kind == ACTOR_PID:
            cls_name = pid[2] if len(pid) > 2 else ""
            return ClientActorHandle(hex_id, self, class_name=cls_name)
        raise ValueError(f"unknown persistent id {pid!r}")

    def _dumps(self, obj) -> bytes:
        return dumps_with_ids(obj, self._id_for)

    def _loads(self, blob: bytes):
        return loads_with_ids(blob, self._load_pid)

    # ------------------------------------------------------------------- api

    def put(self, value: Any) -> ClientObjectRef:
        return self._call("cl_put", {"value": self._dumps(value)})

    # timeout=None on get/wait means block-forever (driver semantics): use an
    # effectively-unbounded wire timeout so the RPC layer's default request
    # timeout can't fire first.
    _FOREVER = 10 * 365 * 24 * 3600.0

    def get(self, refs, *, timeout: Optional[float] = None):
        wire_timeout = self._FOREVER if timeout is None else timeout + 30
        return self._call("cl_get",
                          {"refs": self._dumps(refs), "timeout": timeout},
                          timeout=wire_timeout)

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        wire_timeout = self._FOREVER if timeout is None else timeout + 30
        return self._call(
            "cl_wait",
            {"refs": self._dumps(list(refs)), "num_returns": num_returns,
             "timeout": timeout},
            timeout=wire_timeout)

    def submit_task(self, fn_blob: bytes, fn_name: str, args, kwargs,
                    opts: Dict[str, Any]):
        return self._call("cl_task", {
            "fn": fn_blob, "fn_name": fn_name,
            "args": self._dumps((args, kwargs)),
            "opts": _wire_opts(opts),
        })

    def create_actor(self, cls, args, kwargs, opts: Dict[str, Any]):
        return self._call("cl_actor", {
            "cls": self._dumps(cls),
            "args": self._dumps((args, kwargs)),
            "opts": _wire_opts(opts),
        })

    def actor_call(self, handle: ClientActorHandle, method: str, args, kwargs,
                   *, num_returns: int = 1):
        return self._call("cl_actor_call", {
            "actor": handle._hex, "method": method,
            "args": self._dumps((args, kwargs)),
            "num_returns": num_returns,
        })

    def get_actor(self, name: str, namespace: Optional[str] = None):
        return self._call("cl_named_actor",
                          {"name": name, "namespace": namespace})

    def kill(self, handle: ClientActorHandle, *, no_restart: bool = True):
        self._call("cl_kill", {"actor": handle._hex, "no_restart": no_restart})

    def cancel(self, ref: ClientObjectRef, *, force: bool = False):
        self._call("cl_cancel", {"ref": ref._hex, "force": force})

    def nodes(self):
        return self._call("cl_query", {"kind": "nodes"})

    def cluster_resources(self):
        return self._call("cl_query", {"kind": "cluster_resources"})

    def available_resources(self):
        return self._call("cl_query", {"kind": "available_resources"})

    def disconnect(self):
        try:
            self._call("cl_disconnect", {})
        except Exception:
            pass
        try:
            self._run(self._client.close())
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=2)


def _wire_opts(opts: Dict[str, Any]) -> Dict[str, Any]:
    """Only plain-data options cross the wire."""
    out = {}
    for k, v in (opts or {}).items():
        if isinstance(v, (str, int, float, bool, type(None), dict, list, tuple)):
            out[k] = v
    return out
