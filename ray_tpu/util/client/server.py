"""Client server: lets a remote process drive this cluster without joining it.

TPU-native analog of the reference's Ray Client server
(`python/ray/util/client/server/`): the server process is a real driver
(CoreWorker connected to the cluster); each client session proxies
task-submission / actor / object ops through it over the framework's own RPC
(length-prefixed frames — no gRPC, matching `_private/rpc.py`'s stance).

Run standalone:
    python -m ray_tpu.util.client.server --cluster <host:port> --port 10001

Blocking driver calls (get/wait) run in a thread pool so one slow client
cannot stall the server's event loop.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import pickle
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.util.client.common import (ACTOR_PID, REF_PID, dumps_with_ids,
                                        loads_with_ids)

logger = logging.getLogger(__name__)


class _Session:
    """Per-client state: pinned refs + known actor handles."""

    def __init__(self, session_id: str, namespace: str = "default"):
        self.id = session_id
        self.namespace = namespace
        self.refs: Dict[str, Any] = {}       # hex -> real ObjectRef (pin)
        # pin counts: each serialize of a ref to the client mints one client
        # stub, and each stub GC sends one release — counts must balance or a
        # duplicate stub (e.g. from wait()) would drop a shared pin early
        self.pins: Dict[str, int] = {}
        self.actors: Dict[str, Any] = {}     # hex -> real ActorHandle
        self.last_seen = time.monotonic()


class ClientServer:
    def __init__(self, cluster_address: Optional[str] = None,
                 host: str = "0.0.0.0", port: int = 10001, *,
                 namespace: str = "default", init_kwargs: Optional[dict] = None,
                 session_ttl_s: float = 600.0):
        self._cluster_address = cluster_address
        self._host, self._port = host, port
        self._namespace = namespace
        self._init_kwargs = dict(init_kwargs or {})
        self._session_ttl = session_ttl_s
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._server = None
        self._reaper_task = None

    # ------------------------------------------------------------- pickle glue

    def _session(self, body: Dict[str, Any]) -> _Session:
        sid = body["session"]
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                s = self._sessions[sid] = _Session(
                    sid, body.get("namespace") or self._namespace)
            s.last_seen = time.monotonic()
            return s

    def _session_if_exists(self, body: Dict[str, Any]) -> Optional[_Session]:
        with self._lock:
            s = self._sessions.get(body.get("session", ""))
        if s is not None:
            s.last_seen = time.monotonic()
        return s

    def _id_for(self, session: _Session):
        """persistent_id for server→client payloads: pin refs, map handles."""
        from ray_tpu._private.api import ActorHandle, ObjectRef

        def id_for(obj):
            if isinstance(obj, ObjectRef):
                h = obj.hex()
                session.refs.setdefault(h, obj)
                session.pins[h] = session.pins.get(h, 0) + 1
                return (REF_PID, h)
            if isinstance(obj, ActorHandle):
                session.actors.setdefault(obj._actor_id.hex(), obj)
                return (ACTOR_PID, obj._actor_id.hex(),
                        getattr(obj, "_class_name", ""))
            return None

        return id_for

    def _load_pid(self, session: _Session):
        """persistent_load for client→server payloads."""

        def load(pid):
            kind, hex_id = pid[0], pid[1]
            if kind == REF_PID:
                ref = session.refs.get(hex_id)
                if ref is None:
                    raise KeyError(
                        f"client ref {hex_id[:16]} is not pinned in this "
                        f"session (already released?)")
                return ref
            if kind == ACTOR_PID:
                h = session.actors.get(hex_id)
                if h is None:
                    from ray_tpu._private.api import ActorHandle
                    from ray_tpu._private.ids import ActorID

                    h = ActorHandle(ActorID.from_hex(hex_id))
                    session.actors[hex_id] = h
                return h
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")

        return load

    def _dumps(self, session: _Session, obj) -> bytes:
        return dumps_with_ids(obj, self._id_for(session))

    def _loads(self, session: _Session, blob: bytes):
        return loads_with_ids(blob, self._load_pid(session))

    # ---------------------------------------------------------------- handlers

    async def _wrap(self, session: _Session, fn, *args):
        """Run a blocking driver op off-loop; ship back {ok} or {exc}."""
        try:
            result = await asyncio.to_thread(fn, *args)
            return {"ok": self._dumps(session, result)}
        except BaseException as e:  # noqa: BLE001 — exceptions cross the wire
            try:
                blob = self._dumps(session, e)
            except Exception:
                blob = self._dumps(session, RuntimeError(repr(e)))
            return {"exc": blob}

    async def cl_ping(self, body):
        s = self._session(body)
        import ray_tpu

        return {"pong": True, "namespace": s.namespace,
                "cluster": ray_tpu.is_initialized()}

    async def cl_task(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            args, kwargs = self._loads(s, body["args"])
            opts = body.get("opts") or {}
            core = api._require_core()
            import hashlib

            blob = body["fn"]
            key = hashlib.sha256(blob).hexdigest()
            num_returns = opts.get("num_returns", 1)
            if (num_returns in ("streaming", "dynamic")
                    or (isinstance(num_returns, int) and num_returns < 0)):
                # stream state lives in the owner process; a client://
                # proxy consumer needs per-item forwarding (not yet built)
                raise NotImplementedError(
                    "num_returns='streaming' is not supported in client mode")
            oids = core.submit_task(
                None, args, kwargs,
                name=opts.get("name") or body.get("fn_name", "client_task"),
                num_returns=num_returns,
                resources=api._resources_from_options(opts),
                strategy=api._strategy_from_options(opts),
                max_retries=opts.get("max_retries", -1),
                retry_exceptions=bool(opts.get("retry_exceptions", False)),
                runtime_env=api._resolve_runtime_env(
                    opts.get("runtime_env"), core),
                function_key=key,
                function_blob=blob,
            )
            refs = [api.ObjectRef(oid, core.address) for oid in oids]
            return refs[0] if num_returns == 1 else refs

        return await self._wrap(s, run)

    async def cl_put(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            return api.put(self._loads(s, body["value"]))

        return await self._wrap(s, run)

    async def cl_get(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            refs = self._loads(s, body["refs"])
            return api.get(refs, timeout=body.get("timeout"))

        return await self._wrap(s, run)

    async def cl_wait(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            refs = self._loads(s, body["refs"])
            return api.wait(refs, num_returns=body["num_returns"],
                            timeout=body.get("timeout"))

        return await self._wrap(s, run)

    async def cl_actor(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            cls = loads_with_ids(body["cls"], self._load_pid(s))
            args, kwargs = self._loads(s, body["args"])
            opts = dict(body.get("opts") or {})
            opts.setdefault("namespace", s.namespace)
            handle = api.ActorClass(cls, opts).remote(*args, **kwargs)
            s.actors[handle._actor_id.hex()] = handle
            return handle

        return await self._wrap(s, run)

    async def cl_actor_call(self, body):
        s = self._session(body)

        def run():
            handle = self._load_pid(s)((ACTOR_PID, body["actor"]))
            args, kwargs = self._loads(s, body["args"])
            method = getattr(handle, body["method"])
            if body.get("num_returns", 1) != 1:
                method = method.options(num_returns=body["num_returns"])
            return method.remote(*args, **kwargs)

        return await self._wrap(s, run)

    async def cl_named_actor(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            h = api.get_actor(body["name"],
                              body.get("namespace") or s.namespace)
            s.actors[h._actor_id.hex()] = h
            return h

        return await self._wrap(s, run)

    async def cl_kill(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            h = self._load_pid(s)((ACTOR_PID, body["actor"]))
            api.kill(h, no_restart=body.get("no_restart", True))

        return await self._wrap(s, run)

    async def cl_cancel(self, body):
        s = self._session(body)

        def run():
            from ray_tpu._private import api

            ref = self._load_pid(s)((REF_PID, body["ref"]))
            api.cancel(ref, force=body.get("force", False))

        return await self._wrap(s, run)

    async def cl_query(self, body):
        s = self._session(body)
        kind = body["kind"]

        def run():
            from ray_tpu._private import api

            if kind == "nodes":
                return api.nodes()
            if kind == "cluster_resources":
                return api.cluster_resources()
            if kind == "available_resources":
                return api.available_resources()
            raise ValueError(f"unknown query {kind!r}")

        return await self._wrap(s, run)

    async def cl_release(self, body):
        # must not resurrect a disconnected session as a fresh empty one
        s = self._session_if_exists(body)
        if s is not None:
            for hex_id in body.get("refs", ()):
                n = s.pins.get(hex_id, 0) - 1
                if n <= 0:
                    s.pins.pop(hex_id, None)
                    s.refs.pop(hex_id, None)
                else:
                    s.pins[hex_id] = n
        return {}

    async def cl_disconnect(self, body):
        sid = body["session"]
        with self._lock:
            s = self._sessions.pop(sid, None)
        if s:
            s.refs.clear()
            s.pins.clear()
            s.actors.clear()
        return {}

    # ------------------------------------------------------------------- run

    async def start(self):
        import ray_tpu
        from ray_tpu._private.rpc import RpcServer

        if not ray_tpu.is_initialized():
            # init() drives its own event loops internally — keep it off ours
            kwargs = dict(self._init_kwargs, namespace=self._namespace)
            if self._cluster_address:
                kwargs["address"] = self._cluster_address
            await asyncio.to_thread(lambda: ray_tpu.init(**kwargs))
        self._server = RpcServer(host=self._host, port=self._port)
        for name in dir(self):
            if name.startswith("cl_"):
                self._server.register(name, getattr(self, name))
        addr = await self._server.start()
        self._reaper_task = asyncio.ensure_future(self._reap_sessions())
        logger.info("client server listening on %s:%s", *addr)
        return addr

    async def _reap_sessions(self):
        """Expire sessions whose client vanished without cl_disconnect so
        their pinned refs don't leak for the server's lifetime."""
        while True:
            await asyncio.sleep(min(60.0, self._session_ttl / 4))
            cutoff = time.monotonic() - self._session_ttl
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if s.last_seen < cutoff]
                for sid in dead:
                    s = self._sessions.pop(sid)
                    s.refs.clear()
                    s.pins.clear()
                    s.actors.clear()
            if dead:
                logger.info("reaped %d idle client session(s)", len(dead))

    async def stop(self):
        if self._reaper_task:
            self._reaper_task.cancel()
        if self._server:
            await self._server.stop()


def main(argv=None):
    parser = argparse.ArgumentParser(description="ray_tpu client server")
    parser.add_argument("--cluster", default=None,
                        help="controller host:port (default: start local)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=10001)
    parser.add_argument("--namespace", default="default")
    args = parser.parse_args(argv)

    async def run():
        srv = ClientServer(args.cluster, args.host, args.port,
                           namespace=args.namespace)
        await srv.start()
        print(f"client server ready on {args.host}:{args.port}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
