"""Placement groups: gang-reserving resource bundles across nodes.

Analog of the reference's placement group API
(`python/ray/util/placement_group.py:145`) over the controller's PG manager
(≈ `GcsPlacementGroupManager`). Strategies: PACK, SPREAD, STRICT_PACK,
STRICT_SPREAD.

TPU-first: a pod-slice gang (all hosts of an ICI slice) is expressed as a
STRICT_SPREAD group of per-host bundles each demanding that host's TPU chips,
plus the slice-head resource — see ray_tpu.parallel.slices.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private import api
from ray_tpu._private.exceptions import PlacementGroupError
from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self) -> "api.ObjectRef":
        """An ObjectRef that resolves when the group is placed (≈ pg.ready()).

        Non-blocking: the probe task pends while the group is PENDING (the
        lease path waits for placement) and runs once bundles reserve, so
        ``get(pg.ready(), timeout=...)`` raises GetTimeoutError for an
        unsatisfiable group instead of stalling here.
        """

        @api.remote(num_cpus=0)
        def _pg_ready_probe():
            return True

        return _pg_ready_probe.options(
            scheduling_strategy=None,
            placement_group=self,
        ).remote()

    def wait(self, timeout: float = 30) -> bool:
        """Block until placed. Long-polls the controller's PG-state KV key
        via ``kv_wait`` (one parked RPC) instead of the old 50 ms
        pg_get/sleep loop; pg_get re-checks around each wait slice so a
        missing key (e.g. a controller restart) degrades to slower polls,
        never to a wrong answer."""
        from ray_tpu._private import internal_kv

        core = api._require_core()
        deadline = time.monotonic() + timeout
        while True:
            rec = core._run(
                core.clients.get(core.controller_addr).call(
                    "pg_get", {"pg_id_hex": self.id.hex()}
                )
            )
            if rec and rec["state"] == "CREATED":
                return True
            if rec and rec["state"] == "REMOVED":
                raise PlacementGroupError("placement group was removed")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                internal_kv.kv_wait(self.id.hex(),
                                    timeout=min(remaining, 5.0), ns="pg")
            except TimeoutError:
                pass

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    core = api._require_core()
    pg_id = PlacementGroupID.from_random()
    core._run(
        core.clients.get(core.controller_addr).call(
            "pg_create",
            {
                "pg_id_hex": pg_id.hex(),
                "bundles": [dict(b) for b in bundles],
                "strategy": strategy,
                "name": name,
                "job_id_hex": core.job_id.hex(),
            },
        )
    )
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup) -> None:
    core = api._require_core()
    core._run(
        core.clients.get(core.controller_addr).call(
            "pg_remove", {"pg_id_hex": pg.id.hex()}
        )
    )


def placement_group_table() -> List[dict]:
    core = api._require_core()
    return core._run(core.clients.get(core.controller_addr).call("pg_list"))
