"""State API: list/summarize cluster entities from the driver.

Analog of `python/ray/util/state/api.py` (`ray list tasks`,
`list_actors`, `summary`): thin client functions over the controller's
record tables and task-event sink. Each returns plain dicts so output is
directly printable/serializable.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private import api


def _call(method: str, body: Optional[dict] = None):
    core = api._require_core()
    return core._run(
        core.clients.get(core.controller_addr).call(method, body))


def list_nodes() -> List[Dict[str, Any]]:
    return _call("node_views")


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    records = _call("actor_list")
    out = []
    for rec in records:
        rec = dict(rec)
        rec.pop("creation_spec", None)  # serialized bytes, not listable
        if state is None or rec.get("state") == state:
            out.append(rec)
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    return _call("pg_list")


def list_cluster_events(*, limit: int = 1000,
                        event_type: Optional[str] = None,
                        source_type: Optional[str] = None,
                        severity: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Structured lifecycle events from every daemon, time-ordered
    (≈ `ray list cluster-events`; emitters: _private/events.py)."""
    return _call("events_list", {
        "limit": limit, "event_type": event_type,
        "source_type": source_type, "severity": severity})


def list_jobs() -> List[Dict[str, Any]]:
    return _call("job_list")


def list_tasks(limit: int = 1000,
               name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Task lifecycle events folded to latest-state-per-task
    (≈ `ray list tasks` over the GCS task events)."""
    events = _call("state_tasks", {"limit": limit * 8})
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        latest[ev["task_id"]] = ev
    out = [
        ev for ev in latest.values()
        if name is None or ev.get("name") == name
    ]
    return out[-limit:]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """{task name: {state: count}} (≈ `ray summary tasks`)."""
    summary: Dict[str, _Counter] = {}
    for ev in list_tasks(limit=100_000):
        summary.setdefault(ev["name"], _Counter())[ev["state"]] += 1
    return {k: dict(v) for k, v in summary.items()}


def cluster_metrics() -> str:
    """The controller's Prometheus exposition text."""
    return _call("metrics")


def timeline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace events from the task-event sink (≈ ray.timeline /
    `ray timeline`): load the result into chrome://tracing or Perfetto.

    Each task contributes one duration event per lifecycle span
    (SUBMITTED→PUSHED as 'schedule', PUSHED→FINISHED/FAILED as 'run')
    on a row per worker node. Returns the event list; writes JSON to
    `path` when given.
    """
    events = _call("state_tasks", {"limit": 100_000})
    by_task: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        by_task.setdefault(ev["task_id"], []).append(ev)

    trace: List[Dict[str, Any]] = []
    for task_id, evs in by_task.items():
        evs.sort(key=lambda e: e["ts"])
        stamps = {e["state"]: e for e in evs}
        name = evs[0].get("name", task_id[:8])
        node = evs[0].get("node", "") or "driver"
        spans = [("schedule", "SUBMITTED", ("PUSHED", "RECONSTRUCTING")),
                 ("run", "PUSHED", ("FINISHED", "FAILED"))]
        for label, start_state, end_states in spans:
            start = stamps.get(start_state)
            end = next((stamps[s] for s in end_states if s in stamps), None)
            if start is None or end is None:
                continue
            trace.append({
                "name": f"{name}:{label}",
                "cat": "task",
                "ph": "X",  # complete event
                "ts": start["ts"] * 1e6,   # chrome-trace wants microseconds
                "dur": max(1.0, (end["ts"] - start["ts"]) * 1e6),
                "pid": node[:12],
                "tid": task_id[:8],
                "args": {"task_id": task_id, "state_from": start_state},
            })
    if path:
        import json

        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


# ------------------------------------------------- live worker profiling


def _supervisor_call(node_id_hex: str, method: str, body: dict):
    core = api._require_core()
    node = next((n for n in _call("node_views")
                 if n["node_id_hex"] == node_id_hex), None)
    if node is None:
        raise ValueError(f"node {node_id_hex} not in cluster view")
    return core._run(
        core.clients.get(tuple(node["address"])).call(method, body))


def list_workers(node_id_hex: Optional[str] = None) -> List[Dict[str, Any]]:
    """Live worker processes per node (pid, actor binding)."""
    out = []
    for node in _call("node_views"):
        if node_id_hex and node["node_id_hex"] != node_id_hex:
            continue
        r = _supervisor_call(node["node_id_hex"], "worker_profile", {})
        for w in r["workers"]:
            out.append(dict(w, node_id_hex=node["node_id_hex"]))
    return out


def profile_worker(node_id_hex: str, worker_id_hex: str,
                   kind: str = "stack", limit: int = 20) -> Dict[str, Any]:
    """On-demand live profile of a RUNNING worker — no restart, no
    external profiler (≈ the dashboard's py-spy/memray attach,
    `dashboard/modules/reporter/reporter_agent.py:391`; collectors in
    `_private/profiling.py`). Kinds: "stack" (all thread stacks),
    "memory" (RSS + tracemalloc top sites), "device" (live jax.Array
    HBM breakdown — the TPU question generic profilers can't answer)."""
    return _supervisor_call(node_id_hex, "worker_profile",
                            {"worker_id_hex": worker_id_hex,
                             "kind": kind, "limit": limit})


def profile_actor(name_or_id: str, kind: str = "stack",
                  limit: int = 20) -> Dict[str, Any]:
    """Profile the worker currently hosting an actor (by name or id)."""
    for rec in _call("actor_list"):
        if rec["actor_id_hex"] == name_or_id or rec["name"] == name_or_id:
            if rec["state"] != "ALIVE":
                raise ValueError(
                    f"actor {name_or_id} is {rec['state']}, not ALIVE")
            return profile_worker(rec["node_id_hex"],
                                  rec["worker_id_hex"], kind, limit)
    raise ValueError(f"no actor {name_or_id!r}")
