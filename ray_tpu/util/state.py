"""State API: list/summarize cluster entities from the driver.

Analog of `python/ray/util/state/api.py` (`ray list tasks`,
`list_actors`, `summary`): thin client functions over the controller's
record tables and task-event sink. Each returns plain dicts so output is
directly printable/serializable.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private import api


def _call(method: str, body: Optional[dict] = None):
    core = api._require_core()
    return core._run(
        core.clients.get(core.controller_addr).call(method, body))


def list_nodes() -> List[Dict[str, Any]]:
    return _call("node_views")


def list_actors(state: Optional[str] = None) -> List[Dict[str, Any]]:
    records = _call("actor_list")
    out = []
    for rec in records:
        rec = dict(rec)
        rec.pop("creation_spec", None)  # serialized bytes, not listable
        if state is None or rec.get("state") == state:
            out.append(rec)
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    return _call("pg_list")


def list_cluster_events(*, limit: int = 1000,
                        event_type: Optional[str] = None,
                        source_type: Optional[str] = None,
                        severity: Optional[str] = None
                        ) -> List[Dict[str, Any]]:
    """Structured lifecycle events from every daemon, time-ordered
    (≈ `ray list cluster-events`; emitters: _private/events.py)."""
    return _call("events_list", {
        "limit": limit, "event_type": event_type,
        "source_type": source_type, "severity": severity})


def list_jobs() -> List[Dict[str, Any]]:
    return _call("job_list")


def list_tasks(limit: int = 1000,
               name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Task lifecycle events folded to latest-state-per-task
    (≈ `ray list tasks` over the GCS task events)."""
    events = _call("state_tasks", {"limit": limit * 8})
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        latest[ev["task_id"]] = ev
    out = [
        ev for ev in latest.values()
        if name is None or ev.get("name") == name
    ]
    return out[-limit:]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """{task name: {state: count}} (≈ `ray summary tasks`)."""
    summary: Dict[str, _Counter] = {}
    for ev in list_tasks(limit=100_000):
        summary.setdefault(ev["name"], _Counter())[ev["state"]] += 1
    return {k: dict(v) for k, v in summary.items()}


def cluster_metrics(all_nodes: bool = False) -> str:
    """Prometheus exposition text. Default: the controller's own
    registry (the pre-existing behaviour). ``all_nodes=True`` fans the
    scrape out to every supervisor AND every worker registry (plus this
    driver's own) and merges the expositions with ``node``/``component``
    labels — the data-plane metrics recorded inside worker processes
    (channels, collectives, pipeline, serve, podracer) are otherwise
    invisible cluster-wide."""
    text = _call("metrics")
    if not all_nodes:
        return text
    from ray_tpu._private.metrics import (default_registry,
                                          merge_expositions,
                                          relabel_exposition)

    core = api._require_core()
    parts = [relabel_exposition(
        text, {"node": "head", "component": "controller"})]
    parts.append(relabel_exposition(
        default_registry().render_prometheus(),
        {"node": "head", "component": "driver"}))
    nodes = []
    for node in _call("node_views"):
        if not node.get("alive", True):
            continue  # a dead node's client burns the connect deadline
        name = (node.get("labels") or {}).get("node_name") \
            or node["node_id_hex"][:8]
        nodes.append((name, core.clients.get(tuple(node["address"]))))

    async def _gather_scrapes():
        # concurrent: one wedged supervisor costs its own 30s timeout,
        # not 30s times its position in the node list
        import asyncio

        return await asyncio.gather(
            *(client.call("metrics_all", {}, timeout=30)
              for _, client in nodes),
            return_exceptions=True)

    for (name, _), sections in zip(nodes, core._run(_gather_scrapes())):
        if isinstance(sections, BaseException):
            continue  # a dying node must not fail the cluster scrape
        for component, body in sections:
            parts.append(relabel_exposition(
                body, {"node": name, "component": component}))
    # regroup into one HELP/TYPE block per family: concatenation would
    # emit duplicate TYPE lines, which Prometheus ingestion rejects
    return merge_expositions(parts)


def timeline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-trace events from the task-event sink (≈ ray.timeline /
    `ray timeline`): load the result into chrome://tracing or Perfetto.

    Each task contributes one duration event per lifecycle span
    (SUBMITTED→PUSHED as 'schedule', PUSHED→FINISHED/FAILED as 'run')
    on a row per worker node. Returns the event list; writes JSON to
    `path` when given.
    """
    events = _call("state_tasks", {"limit": 100_000})
    by_task: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        by_task.setdefault(ev["task_id"], []).append(ev)

    trace: List[Dict[str, Any]] = []
    for task_id, evs in by_task.items():
        evs.sort(key=lambda e: e["ts"])
        stamps = {e["state"]: e for e in evs}
        name = evs[0].get("name", task_id[:8])
        node = evs[0].get("node", "") or "driver"
        spans = [("schedule", "SUBMITTED", ("PUSHED", "RECONSTRUCTING")),
                 ("run", "PUSHED", ("FINISHED", "FAILED"))]
        for label, start_state, end_states in spans:
            start = stamps.get(start_state)
            end = next((stamps[s] for s in end_states if s in stamps), None)
            if start is None or end is None:
                continue
            trace.append({
                "name": f"{name}:{label}",
                "cat": "task",
                "ph": "X",  # complete event
                "ts": start["ts"] * 1e6,   # chrome-trace wants microseconds
                "dur": max(1.0, (end["ts"] - start["ts"]) * 1e6),
                "pid": node[:12],
                "tid": task_id[:8],
                "args": {"task_id": task_id, "state_from": start_state},
            })
    if path:
        import json

        with open(path, "w") as f:
            json.dump(trace, f)
    return trace


def flight_timeline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """One merged Chrome-trace/Perfetto timeline of every flight
    recorder in the cluster (`_private/flight.py`): the zero-RPC hot-loop
    spans (channel waits, 1F1B fwd/bwd/flush, serve admit/prefill/decode
    iterations, collective rounds, Sebulba phases) that ``timeline()``'s
    task-event feed cannot see, plus metrics-registry counters sampled at
    drain time and per-flush bubble counter tracks.

    The drain is out-of-band: one ``flight_dump`` RPC per daemon (each
    supervisor relays to its workers), issued only when THIS function
    runs — recording itself never leaves the process. Cross-host clocks
    align via each process's monotonic->wall anchor plus a per-node
    wall-offset handshake with the supervisor, corrected by RTT/2.

    Returns the event list; writes Perfetto-loadable JSON to ``path``
    when given.
    """
    import time as _time

    from ray_tpu._private import flight

    core = api._require_core()
    entries = [(flight.drain(), "head", 0)]
    try:
        controller_dump = _call("flight_dump")
    except Exception:
        controller_dump = None  # controller mid-restart: merge what we can
    nodes = []
    for node in _call("node_views"):
        if not node.get("alive", True):
            # a dead node's client would burn the full connect-retry
            # deadline — worst exactly on the chaos dump-on-failure path
            continue
        addr = tuple(node["address"])
        name = (node.get("labels") or {}).get("node_name") \
            or node["node_id_hex"][:8]
        client = core.clients.get(addr)
        try:
            # RTT/2-corrected wall-clock offset of this node vs the
            # driver's host: the supervisor's clock read is assumed to
            # happen mid-flight, so offset = remote_wall - (t0+t1)/2.
            # Handshakes stay sequential — each needs its own clean RTT
            # measurement, and they are cheap
            t0 = _time.time_ns()
            clock = core._run(client.call("flight_clock", {}, timeout=15))
            t1 = _time.time_ns()
        except Exception:
            continue  # a dying node must not fail the merge
        nodes.append((name, client, int(clock["wall_ns"] - (t0 + t1) // 2),
                      addr))

    if controller_dump is not None:
        # the controller shares the head node's host clock: reuse that
        # supervisor's measured offset (a remotely-attached driver's
        # wall clock can differ from the head's; 0 would skew exactly
        # the controller's rows)
        head_host = core.controller_addr[0]
        head_offset = next((off for _, _, off, a in nodes
                            if a[0] == head_host), 0)
        entries.append((controller_dump, "head", head_offset))

    async def _gather_dumps():
        # the heavy part runs concurrently: total drain time is bounded
        # by the slowest node, not the sum over nodes
        import asyncio

        return await asyncio.gather(
            *(client.call("flight_dump", {"include_workers": True},
                          timeout=60) for _, client, _, _ in nodes),
            return_exceptions=True)

    for (name, _, offset_ns, _), reply in zip(nodes,
                                           core._run(_gather_dumps())):
        if isinstance(reply, BaseException):
            continue  # a dying node must not fail the merge
        for dump in reply.get("dumps", []):
            entries.append((dump, name, offset_ns))
    return flight.merge_dumps(entries, path=path)


# ------------------------------------------------- live worker profiling


def _supervisor_call(node_id_hex: str, method: str, body: dict):
    core = api._require_core()
    node = next((n for n in _call("node_views")
                 if n["node_id_hex"] == node_id_hex), None)
    if node is None:
        raise ValueError(f"node {node_id_hex} not in cluster view")
    return core._run(
        core.clients.get(tuple(node["address"])).call(method, body))


def list_workers(node_id_hex: Optional[str] = None) -> List[Dict[str, Any]]:
    """Live worker processes per node (pid, actor binding)."""
    out = []
    for node in _call("node_views"):
        if node_id_hex and node["node_id_hex"] != node_id_hex:
            continue
        r = _supervisor_call(node["node_id_hex"], "worker_profile", {})
        for w in r["workers"]:
            out.append(dict(w, node_id_hex=node["node_id_hex"]))
    return out


def profile_worker(node_id_hex: str, worker_id_hex: str,
                   kind: str = "stack", limit: int = 20) -> Dict[str, Any]:
    """On-demand live profile of a RUNNING worker — no restart, no
    external profiler (≈ the dashboard's py-spy/memray attach,
    `dashboard/modules/reporter/reporter_agent.py:391`; collectors in
    `_private/profiling.py`). Kinds: "stack" (all thread stacks),
    "memory" (RSS + tracemalloc top sites), "device" (live jax.Array
    HBM breakdown — the TPU question generic profilers can't answer)."""
    return _supervisor_call(node_id_hex, "worker_profile",
                            {"worker_id_hex": worker_id_hex,
                             "kind": kind, "limit": limit})


def profile_actor(name_or_id: str, kind: str = "stack",
                  limit: int = 20) -> Dict[str, Any]:
    """Profile the worker currently hosting an actor (by name or id)."""
    for rec in _call("actor_list"):
        if rec["actor_id_hex"] == name_or_id or rec["name"] == name_or_id:
            if rec["state"] != "ALIVE":
                raise ValueError(
                    f"actor {name_or_id} is {rec['state']}, not ALIVE")
            return profile_worker(rec["node_id_hex"],
                                  rec["worker_id_hex"], kind, limit)
    raise ValueError(f"no actor {name_or_id!r}")
