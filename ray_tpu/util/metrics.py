"""User-defined metrics (≈ `ray.util.metrics` Counter/Gauge/Histogram).

Metrics record into the process-local registry; in daemons they are
served on that daemon's /metrics endpoint, and in driver/worker
processes they can be rendered with `render()` or scraped by whatever
owns the process. Names should be prometheus-safe.
"""

from __future__ import annotations

from ray_tpu._private.metrics import (Counter, Gauge, Histogram,
                                      default_registry)

__all__ = ["Counter", "Gauge", "Histogram", "render"]


def render() -> str:
    """Prometheus text exposition of this process's registry."""
    return default_registry().render_prometheus()
