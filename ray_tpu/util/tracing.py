"""Distributed tracing: span contexts that ride task metadata.

Analog of `python/ray/util/tracing/tracing_helper.py`: when tracing is
enabled, every task/actor submission captures the caller's span context
into the TaskSpec (`trace_ctx`), and the executing worker opens a child
span around the user function — so cross-process call trees stitch into
one trace. Spans export through a pluggable exporter; the default writes
JSON lines to `spans-<pid>.jsonl` in the session log dir, and
`collect_spans()` merges them into a Chrome-trace-compatible list
(`ray timeline`'s span feed). OpenTelemetry, when installed, can be
bridged by passing an exporter that forwards to an otel tracer — the
core never imports otel (the reference lazily imports it the same way,
tracing_helper.py:36-82).

Usage:
    from ray_tpu.util import tracing
    tracing.enable()
    with tracing.span("ingest"):
        ref = my_task.remote(...)       # child span on the worker
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

_current: contextvars.ContextVar[Optional[Dict[str, str]]] = (
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None))

_enabled = False
_exporter: Optional[Callable[[Dict[str, Any]], None]] = None
_lock = threading.Lock()
_file = None
_file_path: Optional[str] = None


def enable(exporter: Optional[Callable[[Dict[str, Any]], None]] = None,
           ) -> None:
    """Turn tracing on in THIS process (drivers and workers each call it;
    workers auto-enable when a traced task arrives)."""
    global _enabled, _exporter
    _enabled = True
    _exporter = exporter


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def _spans_path() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
    d = os.path.join(base, "logs") if os.path.isdir(
        os.path.join(base, "logs")) else base
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"spans-{os.getpid()}.jsonl")


def _emit(span: Dict[str, Any]) -> None:
    global _file, _file_path
    # user spans also land in the flight recorder (when it is on), so
    # they appear on the same merged cluster timeline as the data-plane
    # hot-loop spans — a lock-free ring write, nothing like the
    # file-export cost below
    from ray_tpu._private import flight

    if flight.is_enabled():
        flight.record_span(span["name"], int(span["duration_s"] * 1e9))
    if _exporter is not None:
        _exporter(span)
        return
    with _lock:
        # the session dir can change after init() (spans emitted before
        # init land in the default location) — follow it, don't cache the
        # first resolution forever
        path = _spans_path()
        if _file is None or path != _file_path:
            if _file is not None:
                _file.close()
            _file = open(path, "a", buffering=1)
            _file_path = path
        _file.write(json.dumps(span) + "\n")


def current_context() -> Optional[Dict[str, str]]:
    """The (trace_id, span_id) pair submissions should propagate."""
    return _current.get()


def context_for_submission() -> Optional[Dict[str, str]]:
    """What a task submission should carry: the active span's context, a
    fresh root context when tracing is on but no span is open, or None
    when tracing is off (zero overhead on the untraced path)."""
    if not _enabled:
        return None
    ctx = _current.get()
    if ctx is not None:
        return dict(ctx)
    return {"trace_id": uuid.uuid4().hex, "span_id": ""}


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Open a span; nested spans and remote tasks become children."""
    if not _enabled:
        yield None
        return
    parent = _current.get()
    ctx = {
        "trace_id": (parent or {}).get("trace_id", uuid.uuid4().hex),
        "span_id": uuid.uuid4().hex[:16],
    }
    token = _current.set(ctx)
    start = time.time()
    try:
        yield ctx
    finally:
        _current.reset(token)
        _emit({
            "name": name,
            "trace_id": ctx["trace_id"],
            "span_id": ctx["span_id"],
            "parent_id": (parent or {}).get("span_id"),
            "start_s": start,
            "duration_s": time.time() - start,
            "pid": os.getpid(),
            "attributes": attributes or {},
        })


@contextlib.contextmanager
def remote_span(name: str, trace_ctx: Dict[str, str]):
    """Worker-side: continue a propagated context around task execution."""
    global _enabled
    _enabled = True    # a traced task arriving means tracing is on
    parent_like = {"trace_id": trace_ctx["trace_id"],
                   "span_id": uuid.uuid4().hex[:16]}
    token = _current.set(parent_like)
    start = time.time()
    try:
        yield
    finally:
        _current.reset(token)
        _emit({
            "name": name,
            "trace_id": trace_ctx["trace_id"],
            "span_id": parent_like["span_id"],
            "parent_id": trace_ctx.get("span_id"),
            "start_s": start,
            "duration_s": time.time() - start,
            "pid": os.getpid(),
            "attributes": {"remote": True},
        })


def collect_spans(session_dir: Optional[str] = None) -> List[Dict[str, Any]]:
    """Merge every process's span files (driver + workers) for analysis or
    a Chrome-trace dump."""
    import glob as _glob

    base = session_dir or os.environ.get("RAY_TPU_SESSION_DIR",
                                         "/tmp/ray_tpu")
    out: List[Dict[str, Any]] = []
    for pat in (os.path.join(base, "logs", "spans-*.jsonl"),
                os.path.join(base, "spans-*.jsonl")):
        for f in _glob.glob(pat):
            try:
                with open(f) as fh:
                    for line in fh:
                        line = line.strip()
                        if line:
                            out.append(json.loads(line))
            except OSError:
                continue
    out.sort(key=lambda s: s["start_s"])
    return out


def to_chrome_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans -> chrome://tracing 'X' events (complements the task-event
    timeline in util/state.py)."""
    return [{
        "name": s["name"],
        "cat": "span",
        "ph": "X",
        "ts": s["start_s"] * 1e6,
        "dur": s["duration_s"] * 1e6,
        "pid": s.get("pid", 0),
        "tid": int(s["trace_id"][:6], 16),
        "args": dict(s.get("attributes", {}),
                     trace_id=s["trace_id"], span_id=s["span_id"],
                     parent_id=s.get("parent_id")),
    } for s in spans]
