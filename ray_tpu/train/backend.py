"""Training backends.

Analog of `ray.train.backend.Backend/BackendConfig` plus the torch backend
(`python/ray/train/torch/config.py:150` `_TorchBackend.on_start`, which runs
`dist.init_process_group` on every worker) and the torch-XLA TPU backend
(`python/ray/train/torch/xla/config.py:20`).

TPU-first replacement: the process group IS a `jax.distributed` runtime.
Worker 0 picks a coordinator port; every worker calls
`jax.distributed.initialize(coordinator, num_processes, process_id)` before
the user loop runs, after which `jax.devices()` spans the whole slice and
pjit/GSPMD emit ICI collectives — there is no NCCL layer to bootstrap.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by the BackendExecutor around the worker group."""

    def on_start(self, worker_group, backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group,
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig) -> None:
        pass


# ----------------------------------------------------------------- jax


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """Backend config for JAX SPMD training.

    ``distributed``: form a multi-process `jax.distributed` runtime across
    the workers. ``None`` (default) auto-enables when there is more than one
    worker AND TPU chips are attached — the multi-host case. Single-worker
    runs (one process driving all local chips) skip it: `jax.devices()`
    already sees everything.
    """

    distributed: Optional[bool] = None
    use_tpu: bool = False
    coordinator_port: int = 0  # 0 = pick a free port

    @property
    def backend_cls(self):
        return _JaxBackend


def _find_coordinator(port_hint: int):
    import socket

    host = socket.gethostbyname(socket.gethostname())
    if port_hint:
        return f"{host}:{port_hint}"
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return f"{host}:{port}"


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int) -> bool:
    import jax

    if not jax.distributed.is_initialized():
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return True


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        n = len(worker_group)
        distributed = backend_config.distributed
        if distributed is None:
            distributed = n > 1 and backend_config.use_tpu
        if not distributed:
            return
        coordinator = worker_group.execute_single(
            0, _find_coordinator, backend_config.coordinator_port)
        logger.info("jax.distributed coordinator at %s (%d processes)",
                    coordinator, n)
        import ray_tpu

        ray_tpu.get([
            w.actor.execute_fn.remote(
                _init_jax_distributed, coordinator, n, w.world_rank)
            for w in worker_group.workers
        ])

    def on_shutdown(self, worker_group, backend_config: JaxConfig) -> None:
        def _shutdown():
            try:
                import jax

                if jax.distributed.is_initialized():
                    jax.distributed.shutdown()
            except Exception:
                pass
            return True

        try:
            worker_group.execute(_shutdown)
        except Exception:
            pass
