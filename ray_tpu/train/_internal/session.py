"""Per-worker training session.

Analog of `ray.train._internal.session._TrainSession`
(`python/ray/train/_internal/session.py:110`, `report :666`,
`get_checkpoint :753`): the user's ``train_loop_per_worker`` runs on a
side thread; ``report(metrics, checkpoint)`` persists the checkpoint into
trial storage (worker-side upload, like the reference's StorageContext on
workers) and blocks until the driver has consumed the report — report is
the per-iteration barrier that paces every rank together.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.storage import StorageContext

logger = logging.getLogger(__name__)

_session_lock = threading.Lock()
_session: Optional["_TrainSession"] = None


@dataclasses.dataclass
class TrainingReport:
    kind: str  # "report" | "done" | "error" | "timeout"
    metrics: Optional[Dict[str, Any]] = None
    checkpoint_path: Optional[str] = None  # persisted (storage) path
    error: Optional[str] = None
    final_return: Any = None


class _TrainSession:
    def __init__(
        self,
        train_fn: Callable[[], Any],
        world_rank: int,
        local_rank: int,
        world_size: int,
        local_world_size: int,
        node_rank: int,
        storage: StorageContext,
        experiment_name: str,
        trial_name: str,
        loaded_checkpoint: Optional[Checkpoint] = None,
        trial_info: Optional[Dict[str, Any]] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.world_size = world_size
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.storage = storage
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.loaded_checkpoint = loaded_checkpoint
        self.trial_info = trial_info or {}
        self.dataset_shards = dataset_shards or {}
        # maxsize=1: report() blocks until the driver drains the previous
        # result — backpressure doubles as the cross-rank barrier.
        self._queue: "queue.Queue[TrainingReport]" = queue.Queue(maxsize=1)
        self._train_fn = train_fn
        self._thread: Optional[threading.Thread] = None
        self._finished = threading.Event()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        def _run():
            try:
                ret = self._train_fn()
                self._queue.put(TrainingReport(kind="done", final_return=ret))
            except BaseException as e:  # surfaced to the driver, then re-raised
                logger.error("train fn failed on rank %d:\n%s",
                             self.world_rank, traceback.format_exc())
                self._queue.put(
                    TrainingReport(kind="error",
                                   error=f"{type(e).__name__}: {e}"))
            finally:
                self._finished.set()

        self._thread = threading.Thread(
            target=_run, daemon=True, name=f"train_fn_rank{self.world_rank}")
        self._thread.start()

    def next_report(self, timeout: Optional[float] = None) -> TrainingReport:
        """Driver-driven: block for the next report from the user loop.

        A slow step is NOT a failure: on timeout this returns a
        ``kind="timeout"`` report so the driver can simply re-poll instead
        of misclassifying the rank as dead (ADVICE r1: queue.Empty was
        consuming a FailureConfig retry).
        """
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return TrainingReport(kind="timeout")

    def finished(self) -> bool:
        return self._finished.is_set()

    # ------------------------------------------------------------- user API

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        persisted_path = None
        if checkpoint is not None:
            persisted = self.storage.persist_current_checkpoint(checkpoint)
            persisted_path = persisted.path
            self.loaded_checkpoint = persisted
        # every rank advances its index in lockstep (report is a barrier),
        # so rank-local indices agree without coordination.
        self.storage.advance_checkpoint_index()
        self._queue.put(
            TrainingReport(kind="report", metrics=dict(metrics),
                           checkpoint_path=persisted_path))

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.loaded_checkpoint

    def get_dataset_shard(self, name: str):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(
                f"no dataset shard named {name!r} was passed to the trainer")
        return shard


# ------------------------------------------------------------------ context


class TrainContext:
    """`ray.train.get_context()` analog (`python/ray/train/context.py`)."""

    def _s(self) -> _TrainSession:
        s = get_session()
        if s is None:
            raise RuntimeError(
                "TrainContext is only available inside a training worker")
        return s

    def get_world_size(self) -> int:
        return self._s().world_size

    def get_world_rank(self) -> int:
        return self._s().world_rank

    def get_local_rank(self) -> int:
        return self._s().local_rank

    def get_local_world_size(self) -> int:
        return self._s().local_world_size

    def get_node_rank(self) -> int:
        return self._s().node_rank

    def get_experiment_name(self) -> str:
        return self._s().experiment_name

    def get_trial_name(self) -> str:
        return self._s().trial_name

    def get_trial_info(self) -> Dict[str, Any]:
        return dict(self._s().trial_info)

    def get_storage(self) -> StorageContext:
        return self._s().storage


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        if _session is not None:
            raise RuntimeError("a train session is already active")
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> Optional[_TrainSession]:
    return _session


def shutdown_session() -> None:
    global _session
    with _session_lock:
        _session = None


# ----------------------------------------------------- public free functions


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    s = get_session()
    if s is None:
        raise RuntimeError("train.report() called outside a training worker")
    s.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "train.get_checkpoint() called outside a training worker")
    return s.get_checkpoint()


def get_context() -> TrainContext:
    return TrainContext()


def get_dataset_shard(name: str = "train"):
    s = get_session()
    if s is None:
        raise RuntimeError(
            "train.get_dataset_shard() called outside a training worker")
    return s.get_dataset_shard(name)
