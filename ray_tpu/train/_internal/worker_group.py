"""Actor-based worker group.

Analog of `ray.train._internal.worker_group.WorkerGroup`
(`python/ray/train/_internal/worker_group.py:102`): N long-lived actors,
gang-placed under one placement group, each able to run arbitrary
functions. Ranks are assigned by grouping workers on the same node
(node_rank / local_rank), matching the reference's rank assignment in
`backend_executor.py`.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)

logger = logging.getLogger(__name__)


class RayTrainWorker:
    """The actor body. Hosts a session and executes shipped functions."""

    def __init__(self):
        self._session = None

    def ping(self) -> bool:
        return True

    def node_info(self) -> Dict[str, Any]:
        ctx = ray_tpu.get_runtime_context()
        return {"node_id": ctx.get_node_id(), "pid": __import__("os").getpid()}

    def set_env_vars(self, env: Dict[str, str]) -> bool:
        import os

        os.environ.update(env)
        return True

    def execute_fn(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    # -------------------------------------------------------------- session

    def start_session(self, session_kwargs: Dict[str, Any]) -> bool:
        from ray_tpu.train._internal import session as session_mod

        session_kwargs = dict(session_kwargs)
        gang_pg = session_kwargs.pop("gang_pg", None)
        if gang_pg is not None:
            # this process hosts a Tune trial whose gang PG also covers
            # the trainer's workers (bundles 1..N)
            set_ambient_placement_group(gang_pg, bundle_offset=1)
        self._session = session_mod.init_session(**session_kwargs)
        self._session.start()
        return True

    def next_report(self, timeout: Optional[float] = None):
        assert self._session is not None, "session not started"
        return self._session.next_report(timeout=timeout)

    def end_session(self) -> None:
        from ray_tpu.train._internal import session as session_mod

        set_ambient_placement_group(None)
        session_mod.shutdown_session()
        self._session = None


class WorkerMetadata:
    def __init__(self, actor, node_id: str, pid: int):
        self.actor = actor
        self.node_id = node_id
        self.pid = pid
        self.world_rank = -1
        self.local_rank = -1
        self.node_rank = -1
        self.local_world_size = 1


# Ambient gang placement group: a Tune trial reserves ONE placement group
# covering the trial actor AND its trainer's whole worker gang (bundle 0 =
# trial actor, bundles 1..N = train workers); the trainer's WorkerGroup
# inside the trial joins that group instead of creating its own, so
# concurrent trials can never hold actors while starving each other's
# worker bundles (reference: tune/execution/placement_groups.py).
_ambient_pg: Optional[PlacementGroup] = None
_ambient_bundle_offset: int = 0


def set_ambient_placement_group(pg: Optional[PlacementGroup],
                                bundle_offset: int = 1) -> None:
    global _ambient_pg, _ambient_bundle_offset
    _ambient_pg = pg
    _ambient_bundle_offset = bundle_offset


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Optional[Dict[str, float]] = None,
        placement_strategy: str = "PACK",
        placement_group: Optional[PlacementGroup] = None,
        bundle_offset: int = 0,
    ):
        self._num_workers = num_workers
        self._resources = dict(resources_per_worker or {"CPU": 1.0})
        self._pg: Optional[PlacementGroup] = placement_group
        self._owns_pg = placement_group is None
        self._bundle_offset = bundle_offset
        self.workers: List[WorkerMetadata] = []
        self._placement_strategy = placement_strategy
        if self._pg is None and _ambient_pg is not None:
            self._pg = _ambient_pg
            self._bundle_offset = _ambient_bundle_offset
            self._owns_pg = False

    def start(self, timeout: float = 60.0) -> None:
        if self._owns_pg:
            bundles = [dict(self._resources)
                       for _ in range(self._num_workers)]
            self._pg = placement_group(
                bundles, strategy=self._placement_strategy)
        if not self._pg.wait(timeout=timeout):
            if self._owns_pg:
                remove_placement_group(self._pg)
            raise TimeoutError(
                f"placement group for {self._num_workers} workers "
                f"({self._resources}) not ready in {timeout}s")

        worker_cls = ray_tpu.remote(RayTrainWorker)
        num_cpus = self._resources.get("CPU", 1.0)
        res = {k: v for k, v in self._resources.items() if k != "CPU"}
        actors = [
            worker_cls.options(
                num_cpus=num_cpus,
                resources=res or None,
                placement_group=self._pg,
                placement_group_bundle_index=self._bundle_offset + i,
            ).remote()
            for i in range(self._num_workers)
        ]
        infos = ray_tpu.get([a.node_info.remote() for a in actors])
        self.workers = [
            WorkerMetadata(a, info["node_id"], info["pid"])
            for a, info in zip(actors, infos)
        ]
        self._assign_ranks()

    def _assign_ranks(self) -> None:
        """Stable sort by node so co-located workers get contiguous world
        ranks (ICI-adjacent ranks on one host), then rank within node."""
        by_node: Dict[str, List[WorkerMetadata]] = {}
        for w in self.workers:
            by_node.setdefault(w.node_id, []).append(w)
        self.workers = [w for node in by_node.values() for w in node]
        for node_rank, node in enumerate(by_node.values()):
            for local_rank, w in enumerate(node):
                w.node_rank = node_rank
                w.local_rank = local_rank
                w.local_world_size = len(node)
        for world_rank, w in enumerate(self.workers):
            w.world_rank = world_rank

    def __len__(self) -> int:
        return len(self.workers)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return [
            w.actor.execute_fn.remote(fn, *args, **kwargs)
            for w in self.workers
        ]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return ray_tpu.get(
            self.workers[rank].actor.execute_fn.remote(fn, *args, **kwargs))

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w.actor)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None and self._owns_pg:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
        self._pg = None
