"""Top-k checkpoint retention.

Analog of `ray.train._internal.checkpoint_manager.CheckpointManager`
(`python/ray/train/_internal/checkpoint_manager.py`): orders reported
checkpoints by a score attribute, keeps ``num_to_keep``, deletes evicted
checkpoint directories from storage.
"""

from __future__ import annotations

import logging
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.config import CheckpointConfig
from ray_tpu.train._checkpoint import Checkpoint

logger = logging.getLogger(__name__)


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: Dict[str, Any],
                 index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    def __init__(self, checkpoint_config: Optional[CheckpointConfig] = None):
        self._config = checkpoint_config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._latest: Optional[_TrackedCheckpoint] = None

    def register_checkpoint(
        self, checkpoint: Checkpoint, metrics: Dict[str, Any], index: int
    ) -> None:
        tracked = _TrackedCheckpoint(checkpoint, metrics, index)
        self._latest = tracked
        self._checkpoints.append(tracked)
        self._enforce_retention()

    def _score(self, t: _TrackedCheckpoint) -> float:
        attr = self._config.checkpoint_score_attribute
        if attr is None:
            return float(t.index)  # recency
        try:
            v = float(t.metrics[attr])
        except (KeyError, TypeError, ValueError):
            logger.warning(
                "checkpoint %d has no numeric metric %r; scoring lowest",
                t.index, attr)
            return float("-inf")
        return v if self._config.checkpoint_score_order == "max" else -v

    def _enforce_retention(self) -> None:
        keep = self._config.num_to_keep
        if keep is None or len(self._checkpoints) <= keep:
            return
        ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        survivors = ranked[:keep]
        # the latest checkpoint is always kept (needed for resume)
        if self._latest is not None and self._latest not in survivors:
            survivors[-1] = self._latest
        for t in self._checkpoints:
            if t not in survivors:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._checkpoints = [t for t in self._checkpoints if t in survivors]

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self._latest.checkpoint if self._latest else None

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=self._score).checkpoint

    @property
    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        return [(t.checkpoint, t.metrics) for t in ranked]
