"""Drives a worker group through one training run.

Analog of `ray.train._internal.backend_executor.BackendExecutor`
(`python/ray/train/_internal/backend_executor.py:124` start, `:436`
start_training): starts the gang, runs backend setup, ships the session to
every worker, then pumps reports until all ranks finish. Worker death
raises TrainingWorkerError; the trainer layer decides whether to restart
(FailureConfig).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.session import TrainingReport
from ray_tpu.train._internal.storage import StorageContext
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig

logger = logging.getLogger(__name__)


class TrainingWorkerError(RuntimeError):
    """A worker failed (actor death or user-code exception)."""


class TrainingFinished(Exception):
    """All ranks returned from the user loop."""

    def __init__(self, finals: List[Any]):
        self.finals = finals
        super().__init__("training finished")


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        storage: StorageContext,
        experiment_name: str,
        trial_name: str,
    ):
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._storage = storage
        self._experiment_name = experiment_name
        self._trial_name = trial_name
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.worker_group = WorkerGroup(
            num_workers=self._scaling.num_workers,
            resources_per_worker=self._scaling._worker_bundle,
            placement_strategy=self._scaling.placement_strategy,
        )
        self.worker_group.start()
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(
        self,
        train_fn: Callable[[Optional[Dict]], Any],
        train_fn_config: Optional[Dict[str, Any]],
        checkpoint: Optional[Checkpoint],
        dataset_shards_per_worker: Optional[List[Dict[str, Any]]] = None,
        checkpoint_index: int = 0,
    ) -> None:
        assert self.worker_group is not None, "call start() first"
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        import functools

        refs = []
        for w in self.worker_group.workers:
            storage = StorageContext(
                self._storage.storage_path,
                self._storage.experiment_dir_name,
                self._storage.trial_dir_name,
            )
            storage.current_checkpoint_index = checkpoint_index
            storage.make_dirs()
            shards = (dataset_shards_per_worker[w.world_rank]
                      if dataset_shards_per_worker else {})
            kwargs = dict(
                train_fn=functools.partial(train_fn, train_fn_config)
                if train_fn_config is not None else train_fn,
                world_rank=w.world_rank,
                local_rank=w.local_rank,
                world_size=len(self.worker_group),
                local_world_size=w.local_world_size,
                node_rank=w.node_rank,
                storage=storage,
                experiment_name=self._experiment_name,
                trial_name=self._trial_name,
                loaded_checkpoint=checkpoint,
                dataset_shards=shards,
            )
            refs.append(w.actor.start_session.remote(kwargs))
        ray_tpu.get(refs)

    def get_next_results(self,
                         poll_interval: float = 60.0) -> List[TrainingReport]:
        """One synchronized round: one report per rank.

        Ranks are polled with a short RPC timeout; a rank whose step/ckpt
        takes longer just returns ``kind="timeout"`` and is re-polled, so a
        slow step is never misclassified as a death (only an actual actor
        death raises TrainingWorkerError). Raises TrainingFinished when
        every rank's loop returned.
        """
        assert self.worker_group is not None
        workers = self.worker_group.workers
        reports: List[Optional[TrainingReport]] = [None] * len(workers)
        pending = list(range(len(workers)))
        while pending:
            refs = [
                workers[i].actor.next_report.remote(poll_interval)
                for i in pending
            ]
            try:
                got: List[TrainingReport] = ray_tpu.get(refs)
            except Exception as e:
                raise TrainingWorkerError(
                    f"training worker died: {e}") from e
            still = []
            for i, rep in zip(pending, got):
                if rep.kind == "timeout":
                    still.append(i)
                else:
                    reports[i] = rep
                    # Fail fast: one rank erroring can leave SPMD peers
                    # blocked in a collective forever — don't wait for them.
                    if rep.kind == "error":
                        raise TrainingWorkerError(
                            f"rank {i} failed: {rep.error}")
            pending = still
        errors = [r for r in reports if r.kind == "error"]
        if errors:
            raise TrainingWorkerError(
                f"{len(errors)}/{len(reports)} ranks failed: "
                + "; ".join(r.error for r in errors[:3]))
        done = [r for r in reports if r.kind == "done"]
        if done:
            if len(done) != len(reports):
                # some ranks returned while others reported — drain mismatch
                raise TrainingWorkerError(
                    "ranks desynchronized: some finished while others "
                    "are still reporting (uneven report() counts)")
            raise TrainingFinished([r.final_return for r in reports])
        return reports

    def shutdown(self) -> None:
        if self.worker_group is None:
            return
        try:
            self._backend.on_shutdown(self.worker_group, self._backend_config)
        except Exception:
            pass
        try:
            for w in self.worker_group.workers:
                w.actor.end_session.remote()
        except Exception:
            pass
        self.worker_group.shutdown()
        self.worker_group = None
