"""MPMD pipeline-parallel training: 1F1B microbatches over channels.

Reproduces the topology of "Scaling Deep Learning Training with MPMD
Pipeline Parallelism" (arXiv:2412.14374) on this framework's fast-path
substrate: S stage actors each own ONE model shard, forward activations
and backward gradients flow stage-to-stage through compiled-graph
channels (`_private/channels.py` — pin-backed seqlock slot rings, NOT the
object store), and each stage's long-running run loop executes an EAGER
1F1B microbatch schedule: backward as soon as its gradient is committed
(gradients still accumulate in microbatch order, so numerics are
deterministic), otherwise forwards ahead bounded by the channel depth —
so roughly S - s (at most depth) microbatches of activation stash live
on stage s. Optional intra-stage data parallelism rides the p2p
collective layer: dp replicas of every stage sync their accumulated
gradients with one `allreduce_coalesced_async(op=MEAN)` at flush.

The steady-state cost model is the whole point: one microbatch hop is a
channel write + a channel read (same-node: two shared-memory seqlock
ops; cross-node: one pre-established push over the chunked transfer
window). A steady flush issues ZERO control-plane RPCs per stage rank —
counter-proven via ``ray_tpu_rpc_client_calls_total`` deltas carried in
each stage's per-flush report. Contrast `parallel/pipeline.py`, the
SPMD-inside-one-jit GPipe over a `pp` mesh axis: that recipe needs every
stage on one jit-reachable mesh; this one composes independent
processes/hosts, which is what the MPMD paper is about.

Channel depth: 1F1B needs capacity for several in-flight microbatches
per edge, so the trainer compiles its channels at depth
``max(2, min(S + 1, M))`` by default (the PR-8 slot ring). Depth 1 would
still be deadlock-free — the schedule degenerates to lockstep — but
serializes the pipeline; the microbenchmark guard asserts depth > 1 so
an accidental fallback can't vacuously pass.

Failure semantics match compiled DAGs: teardown or any participant's
death closes every channel (supervisor participant registry + a
driver-side actor-state subscription), blocked peers raise
``ChannelClosedError`` instead of hanging, and the per-flush gradient
state is discarded — a broken pipeline can produce an error, never a
wrong loss.

``mode="tasks"`` runs the SAME stage math as dynamic actor tasks through
the object store (per-microbatch per-stage `.remote()` calls) — the
baseline `pipeline_task_per_stage_step` microbenchmark probe and a
debugging aid, not a fallback: channel compilation failures raise.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import channels as _channels
from ray_tpu._private import chaos, flight, serialization
from ray_tpu._private.exceptions import ChannelClosedError
from ray_tpu._private.metrics import Counter, Gauge, Histogram

logger = logging.getLogger(__name__)

# flight-recorder span ids (per-thread ring records, zero RPCs): the
# per-microbatch phases the aggregate bubble gauge can't localize
_F_FWD = flight.intern("pipe.fwd")
_F_BWD = flight.intern("pipe.bwd")
_F_FLUSH = flight.intern("pipe.flush")
_F_OPT = flight.intern("pipe.opt")
_F_DP = flight.intern("pipe.dp_allreduce")
_F_BUBBLE = flight.intern("pipe.bubble_bp")

_m_microbatches = Counter(
    "ray_tpu_pipeline_microbatches_total",
    "Pipeline microbatches processed, by stage rank")
_m_flushes = Counter(
    "ray_tpu_pipeline_flushes_total",
    "Pipeline flushes (optimizer steps) completed, by stage rank")
_m_stage_seconds = Histogram(
    "ray_tpu_pipeline_stage_step_seconds",
    "Per-stage wall seconds for one flush (M microbatches + optimizer)")
_m_bubble = Gauge(
    "ray_tpu_pipeline_bubble_fraction",
    "Fraction of the last flush a stage spent blocked on channel "
    "waits (the pipeline bubble, measured not estimated)")


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage's model shard as pure, PICKLABLE callables
    (module-level functions / functools.partial — they ship to the stage
    actor). Stages 0..S-2 define ``fwd``; the last stage defines
    ``loss``.

      init()                  -> params pytree (this shard only)
      fwd(params, x)          -> y activations (differentiable in both)
      loss(params, x, labels) -> scalar loss (differentiable in p and x)
    """

    init: Callable[[], Any]
    fwd: Optional[Callable[[Any, Any], Any]] = None
    loss: Optional[Callable[[Any, Any, Any], Any]] = None


def _as_stage_spec(obj) -> StageSpec:
    if isinstance(obj, StageSpec):
        return obj
    if isinstance(obj, dict):
        return StageSpec(init=obj["init"], fwd=obj.get("fwd"),
                         loss=obj.get("loss"))
    raise TypeError(f"not a stage spec: {obj!r}")


@dataclasses.dataclass
class _StagePlan:
    """Wire-shippable channel plan for one stage actor's run loop."""

    in_spec: Optional[_channels.ChannelSpec]  # driver -> stage 0
    label_spec: Optional[_channels.ChannelSpec]  # driver -> last stage
    act_in: Optional[_channels.ChannelSpec]  # stage s-1 -> s
    act_out: Optional[_channels.ChannelSpec]  # stage s -> s+1
    grad_in: Optional[_channels.ChannelSpec]  # stage s+1 -> s
    grad_out: Optional[_channels.ChannelSpec]  # stage s -> s-1
    report: _channels.ChannelSpec  # stage s -> driver, one per flush


# --------------------------------------------------------------- stage math


class _StageRuntime:
    """One stage's compute state: the shard params, jitted fwd/bwd (bwd
    recomputes the stage forward from the stashed INPUT activation —
    full-remat 1F1B, so the stash is one input per in-flight microbatch,
    never the whole residual tree), gradient accumulator, optimizer."""

    def __init__(self, spec: StageSpec, stage: int, num_stages: int,
                 num_microbatches: int, optimizer, dp: int, dp_rank: int,
                 group_name: str):
        import jax

        self.spec = spec
        self.stage = int(stage)
        self.S = int(num_stages)
        self.M = int(num_microbatches)
        self.first = self.stage == 0
        self.last = self.stage == self.S - 1
        self.dp = int(dp)
        self.dp_rank = int(dp_rank)
        self.group_name = group_name
        self._group_ready = False
        self.params = spec.init()
        self._stash: Dict[int, Any] = {}
        self._acc = None
        self._losses: List[float] = []
        self._optimizer = optimizer
        self._opt = None
        self._opt_state = None
        self._update = None

        def tree_add(a, b):
            return jax.tree.map(lambda x, y: x + y, a, b)

        # The gradient ACCUMULATION is fused into the backward jit (one
        # dispatch per microbatch, XLA folds the add into the vjp) with
        # the running accumulator donated in place. Two variants each:
        # the flush's first microbatch has no accumulator yet.
        if self.last:
            if spec.loss is None:
                raise ValueError(
                    f"stage {stage} is the last of {num_stages} and needs "
                    f"a loss callable")
            lg = jax.value_and_grad(spec.loss, argnums=(0, 1))

            def _lg_first(p, x, labels):
                loss, (gp, gx) = lg(p, x, labels)
                return loss, gx, gp

            def _lg_acc(p, x, labels, acc):
                loss, (gp, gx) = lg(p, x, labels)
                return loss, gx, tree_add(acc, gp)

            self._lg_first = jax.jit(_lg_first)
            self._lg_acc = jax.jit(_lg_acc, donate_argnums=3)
        else:
            if spec.fwd is None:
                raise ValueError(f"stage {stage} needs a fwd callable")
            self._fwd = jax.jit(spec.fwd)
            fwd = spec.fwd
            if self.first:
                # input is raw data (tokens): no gradient flows past it
                def _bwd_first(p, x, gy):
                    _, vjp = jax.vjp(lambda pp: fwd(pp, x), p)
                    (gp,) = vjp(gy)
                    return None, gp

                def _bwd_acc(p, x, gy, acc):
                    _, vjp = jax.vjp(lambda pp: fwd(pp, x), p)
                    (gp,) = vjp(gy)
                    return None, tree_add(acc, gp)
            else:
                def _bwd_first(p, x, gy):
                    _, vjp = jax.vjp(fwd, p, x)
                    gp, gx = vjp(gy)
                    return gx, gp

                def _bwd_acc(p, x, gy, acc):
                    _, vjp = jax.vjp(fwd, p, x)
                    gp, gx = vjp(gy)
                    return gx, tree_add(acc, gp)
            self._bwd_first = jax.jit(_bwd_first)
            self._bwd_acc = jax.jit(_bwd_acc, donate_argnums=3)

    # -- per-microbatch

    def forward(self, m: int, x) -> Any:
        """Non-last stages: y = fwd(params, x); stash x for the backward
        recompute."""
        y = self._fwd(self.params, x)
        self._stash[m] = x
        return y

    def loss_backward(self, x, labels) -> Tuple[float, Any]:
        """Last stage only: loss + grads (+ fused accumulation) in one
        jit call (fwd and bwd of the last stage are adjacent in 1F1B, so
        there is nothing to stash)."""
        if self._acc is None:
            loss, gx, self._acc = self._lg_first(self.params, x, labels)
        else:
            loss, gx, self._acc = self._lg_acc(
                self.params, x, labels, self._acc)
        self._losses.append(float(loss))
        return float(loss), gx

    def backward(self, m: int, gy) -> Any:
        """Recompute this stage's forward from the stashed input, apply
        the vjp, fold the param grads into the accumulator; returns the
        input gradient (None at stage 0)."""
        x = self._stash.pop(m)
        if self._acc is None:
            gx, self._acc = self._bwd_first(self.params, x, gy)
        else:
            gx, self._acc = self._bwd_acc(self.params, x, gy, self._acc)
        return gx

    # -- flush

    def _ensure_group(self) -> None:
        if self.dp > 1 and not self._group_ready:
            from ray_tpu.util import collective as col

            col.init_collective_group(
                self.dp, self.dp_rank, backend="host",
                group_name=self.group_name)
            self._group_ready = True

    def _ensure_opt(self) -> None:
        if self._opt is not None:
            return
        import jax
        import optax

        if callable(self._optimizer):
            opt = self._optimizer()
        else:
            kind, lr = self._optimizer
            if kind != "sgd":
                raise ValueError(f"unknown optimizer {kind!r}")
            opt = optax.sgd(lr)
        self._opt = opt
        self._opt_state = opt.init(self.params)

        def update(params, opt_state, grads):
            updates, new_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state

        self._update = jax.jit(update)

    def flush(self, timeout_ms: int = 120_000) -> Dict[str, Any]:
        """Average the accumulated grads over M microbatches (and the dp
        replica group when dp > 1), apply the optimizer, reset."""
        import jax

        if self._stash:
            raise RuntimeError(
                f"stage {self.stage}: flush with {len(self._stash)} "
                f"unconsumed activation stashes (schedule bug)")
        grads = self._acc
        self._acc = None
        if grads is None:
            raise RuntimeError(f"stage {self.stage}: flush with no grads")
        scale = 1.0 / self.M
        grads = jax.tree.map(lambda g: g * scale, grads)
        if self.dp > 1:
            from ray_tpu.util import collective as col
            from ray_tpu.util.collective.types import ReduceOp

            self._ensure_group()
            leaves, treedef = jax.tree.flatten(grads)
            t0 = flight.now()
            work = col.allreduce_coalesced_async(
                leaves, group_name=self.group_name, op=ReduceOp.MEAN,
                timeout_ms=timeout_ms)
            reduced = work.wait(timeout_ms)
            flight.span_since(_F_DP, t0)
            grads = jax.tree.unflatten(treedef, reduced)
        self._ensure_opt()
        self.params, self._opt_state = self._update(
            self.params, self._opt_state, grads)
        losses, self._losses = self._losses, []
        return {"loss_sum": float(np.sum(losses)) if losses else 0.0,
                "microbatches": self.M}


# ----------------------------------------------------- worker-side run loop


# version-addressed local-or-mirror channel writer, shared with the
# compiled-DAG and podracer layers (_private/channels.py)
_Writer = _channels.VersionedWriter


def _copy_tree(value):
    """Deep-copy ndarray leaves out of the shared arena so the channel
    can be acked (and the writer may overwrite) while the value lives
    on."""
    if isinstance(value, np.ndarray):
        return np.array(value)
    if isinstance(value, dict):
        return {k: _copy_tree(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_copy_tree(v) for v in value)
    return value


def _run_stage_loop(rt: _StageRuntime, plan: _StagePlan) -> dict:
    """The per-actor eager-1F1B run loop (occupies the stage actor until
    its channels close): per flush, run backwards the moment their
    gradients are committed and forwards ahead up to the channel-depth
    in-flight bound, then the optimizer flush and one report write.
    Steady flushes touch channels and local compute only — the per-flush
    report carries this rank's observed
    ``ray_tpu_rpc_client_calls_total`` delta as proof."""
    from ray_tpu._private import api, rpc

    core = api._core
    if core is None:
        raise RuntimeError("pipeline stage loop outside a worker process")

    open_local, local, release_pins = _channels.open_local_factory(core)

    def open_reader(spec) -> Optional[_channels.LocalChannel]:
        return open_local(spec) if spec is not None else None

    remote_specs: List[_channels.ChannelSpec] = []

    def writer(spec) -> Optional[_Writer]:
        if spec is None:
            return None
        w = _Writer(core, spec, open_local)
        if w._mirror is not None:
            remote_specs.append(spec)
        return w

    s, S, M = rt.stage, rt.S, rt.M
    stage_label = {"stage": str(s)}
    try:
        in_ch = open_reader(plan.in_spec)
        label_ch = open_reader(plan.label_spec)
        act_in = open_reader(plan.act_in)
        grad_in = open_reader(plan.grad_in)
        act_out = writer(plan.act_out)
        grad_out = writer(plan.grad_out)
        report_w = writer(plan.report)
    except BaseException:
        release_pins()
        raise

    def close_everything() -> None:
        _channels.close_channels_nowait(core, local.values(), remote_specs)

    wait_box = [0.0]
    first_read = [False]  # True while waiting on the flush's FIRST read
    t_box = [0.0]

    def read_value(ch: _channels.LocalChannel, version: int):
        t0 = time.perf_counter()
        view = ch.read(version)
        if first_read[0]:
            # the wait for a flush's first input spans the driver's
            # think-time between step() calls — that's idle, not
            # pipeline bubble; start the flush clock here instead
            first_read[0] = False
            t_box[0] = time.perf_counter()
        else:
            wait_box[0] += time.perf_counter() - t0
        value = _copy_tree(serialization.unpack(view))
        del view
        ch.ack(0, version)
        return value

    def write_value(w: _Writer, value, version: int) -> None:
        payload = serialization.pack(np.asarray(value))
        t0 = time.perf_counter()
        w.write(payload, version)
        wait_box[0] += time.perf_counter() - t0

    flush_idx = 0
    microbatches = 0
    try:
        while True:
            chaos.maybe_crash("worker.pipeline_step")
            t_fl = flight.now()
            t_box[0] = time.perf_counter()
            cpu0 = time.process_time()
            wait_box[0] = 0.0
            first_read[0] = True
            rpc_before = rpc._m_client_calls.total()
            vbase = 2 * (flush_idx * M + 1)
            fwd_m, bwd_m = [0], [0]

            def forward():
                t_mb = flight.now()
                m = fwd_m[0]
                fwd_m[0] += 1
                v = vbase + 2 * m
                x = read_value(in_ch if rt.first else act_in, v)
                if rt.last:
                    labels = read_value(label_ch, v)
                    _, gx = rt.loss_backward(x, labels)
                    write_value(grad_out, gx, v)
                else:
                    write_value(act_out, rt.forward(m, x), v)
                _m_microbatches.inc(labels=stage_label)
                flight.span_since(_F_FWD, t_mb)

            def backward():
                m = bwd_m[0]
                bwd_m[0] += 1
                if rt.last:
                    return  # folded into forward (fwd/bwd adjacent)
                t_mb = flight.now()
                v = vbase + 2 * m
                gy = read_value(grad_in, v)
                gx = rt.backward(m, gy)
                if not rt.first:
                    write_value(grad_out, gx, v)
                flight.span_since(_F_BWD, t_mb)

            # Eager 1F1B: backward-first whenever the grad is already
            # committed (it frees a stash slot and feeds upstream),
            # otherwise run forwards ahead up to the channel-depth
            # in-flight bound. Strict 1F1B's fwd/bwd lockstep costs a
            # full pipeline round-trip of blocking per steady pair; the
            # eager order is the same math (backwards still run in
            # microbatch order, so accumulation is deterministic) under
            # the same memory bound — it just never parks while useful
            # work is ready. When nothing is ready, block on the edge
            # that must deliver next.
            limit = max(1, min(
                M, (plan.act_out or plan.grad_out or plan.report).depth))
            fwd_src = in_ch if rt.first else act_in
            while bwd_m[0] < M:
                progressed = False
                if fwd_m[0] < M and fwd_m[0] - bwd_m[0] < limit \
                        and fwd_src.ready(vbase + 2 * fwd_m[0]):
                    forward()
                    progressed = True
                if bwd_m[0] < fwd_m[0] and (
                        rt.last or grad_in.ready(vbase + 2 * bwd_m[0])):
                    backward()
                    progressed = True
                if progressed:
                    continue
                # nothing committed yet: park on the required edge
                if bwd_m[0] < fwd_m[0] and (
                        fwd_m[0] == M or fwd_m[0] - bwd_m[0] >= limit):
                    backward()
                else:
                    forward()

            microbatches += M
            t_opt = flight.now()
            flush_stats = rt.flush()
            flight.span_since(_F_OPT, t_opt)
            total_s = time.perf_counter() - t_box[0]
            bubble = min(1.0, wait_box[0] / max(total_s, 1e-9))
            # per-flush bubble as a counter track (basis points) — the
            # driver-side merge renders it alongside the wait spans it
            # is derived from
            flight.counter(_F_BUBBLE, int(bubble * 10_000))
            _m_flushes.inc(labels=stage_label)
            _m_stage_seconds.observe(total_s, labels=stage_label)
            _m_bubble.set(bubble, labels=stage_label)
            report = {
                "stage": s,
                "flush": flush_idx,
                "loss_sum": flush_stats["loss_sum"],
                "microbatches": M,
                "rpc_calls": rpc._m_client_calls.total() - rpc_before,
                "wait_s": wait_box[0],
                "flush_s": total_s,
                "cpu_s": time.process_time() - cpu0,
                "bubble_fraction": bubble,
                # this rank's registry values ride along so tests (and
                # the driver) can assert the wiring without an RPC to
                # the worker's /metrics endpoint
                "metrics": {
                    "microbatches_total": _m_microbatches.value(
                        labels=stage_label),
                    "flushes_total": _m_flushes.value(labels=stage_label),
                    "stage_seconds_count":
                        _m_stage_seconds.count_total(),
                },
            }
            report_w.write(serialization.pack(report), 2 * (flush_idx + 1))
            flight.span_since(_F_FLUSH, t_fl)
            flush_idx += 1
    except ChannelClosedError:
        # normal exit: trainer teardown (or a peer's death) closed the
        # channels; a half-done flush's gradient state dies with us.
        # Close OUR channels too before leaving: a peer that poisoned
        # only its own edges (user exception on a still-alive actor —
        # no supervisor death fan-out) relies on each stage propagating
        # the close, or the driver's untimed report read would hang.
        # Safe on the teardown path too: our pins (released in the
        # finally below, after this) keep the ranges alive, and the
        # driver frees them only after collecting this loop's result.
        try:
            close_everything()
        except Exception:
            logger.exception("pipeline close-on-exit failed")
        return {"flushes": flush_idx, "microbatches": microbatches}
    except BaseException:
        # stage math raised: poison the pipeline so every peer (and the
        # driver) unwinds instead of hanging, surface through this task
        try:
            close_everything()
        except Exception:
            logger.exception("pipeline close-on-error failed")
        raise
    finally:
        release_pins()


# ------------------------------------------------------------- stage actor


def _make_runtime(spec_blob, stage, num_stages, num_microbatches,
                  optimizer, dp, dp_rank, group_name) -> _StageRuntime:
    return _StageRuntime(
        _as_stage_spec(spec_blob), stage, num_stages, num_microbatches,
        optimizer, dp, dp_rank, group_name)


class _PipelineStageActorImpl:
    """Stage actor body (wrapped by ray_tpu.remote at trainer build so
    importing this module never requires an initialized runtime)."""

    def __init__(self, spec_blob, stage, num_stages, num_microbatches,
                 optimizer, dp, dp_rank, group_name):
        self._rt = _make_runtime(spec_blob, stage, num_stages,
                                 num_microbatches, optimizer, dp, dp_rank,
                                 group_name)

    def ping(self):
        return "ok"

    def run_loop(self, plan: _StagePlan) -> dict:
        return _run_stage_loop(self._rt, plan)

    # -- dynamic task-per-stage path (microbenchmark baseline; same math)

    def naive_fwd(self, m, x):
        return np.asarray(self._rt.forward(m, np.asarray(x)))

    def naive_loss_bwd(self, m, x, labels):
        _, gx = self._rt.loss_backward(np.asarray(x), np.asarray(labels))
        return np.asarray(gx)

    def naive_bwd(self, m, gy):
        gx = self._rt.backward(m, np.asarray(gy))
        return None if gx is None else np.asarray(gx)

    def naive_flush(self):
        return self._rt.flush()

    # -- introspection (valid before the loop starts or after it exits)

    def fetch_params(self):
        import jax

        return jax.tree.map(np.asarray, self._rt.params)


_stage_actor_cls = None


def _stage_actor():
    global _stage_actor_cls
    if _stage_actor_cls is None:
        import ray_tpu

        _stage_actor_cls = ray_tpu.remote(_PipelineStageActorImpl)
    return _stage_actor_cls


# ------------------------------------------------------------------ trainer


class PipelineTrainer:
    """Train a model sharded over S pipeline stages with 1F1B microbatch
    scheduling over compiled-graph channels (module docstring has the
    design; `ray_tpu.models.presets.pipeline_stage_defs` partitions the
    transformer family into stage specs).

        stages = presets.pipeline_stage_defs(cfg, num_stages=4)
        trainer = PipelineTrainer(stages, num_microbatches=8)
        for batch in data:                # {"tokens": [B, L] int32}
            out = trainer.step(batch)    # {"loss": ..., "reports": [...]}
        trainer.shutdown()

    ``dp`` replicates every stage; replicas sync gradients at flush with
    one coalesced-mean p2p allreduce per stage group. ``mode="tasks"``
    runs the same stage math as dynamic actor tasks through the object
    store (the microbenchmark baseline).
    """

    def __init__(self, stages: Sequence[Any], *, num_microbatches: int,
                 dp: int = 1, mode: str = "channels",
                 optimizer: Any = ("sgd", 0.1),
                 channel_depth: Optional[int] = None,
                 buffer_bytes: Optional[int] = None,
                 stage_options: Optional[Sequence[dict]] = None,
                 name: str = "pipeline"):
        from ray_tpu._private import api

        if mode not in ("channels", "tasks"):
            raise ValueError(f"unknown mode {mode!r}")
        self._specs = [_as_stage_spec(s) for s in stages]
        self._S = len(self._specs)
        if self._S < 2:
            raise ValueError(
                "PipelineTrainer needs >= 2 stages (single-stage training "
                "has no pipeline; use JaxTrainer / models.training)")
        self._M = int(num_microbatches)
        if self._M < 1:
            raise ValueError("num_microbatches must be >= 1")
        self._dp = int(dp)
        self._mode = mode
        self._name = name
        core = api._require_core()
        self._core = core
        self._buffer = int(buffer_bytes or core.config.channel_buffer_bytes)
        cfg_depth = int(core.config.channel_depth or 1)
        # 1F1B wants room for the in-flight microbatch differential; the
        # config knob only wins when the operator raised it higher
        self._depth = int(channel_depth) if channel_depth is not None \
            else max(2, min(self._S + 1, self._M), cfg_depth)
        if self._depth < 1:
            raise ValueError("channel_depth must be >= 1")
        self._flush = 0
        self._dead = False
        self._torn = False
        self._teardown_lock = threading.Lock()
        self._all_specs: List[_channels.ChannelSpec] = []
        self._local_channels: Dict[bytes, _channels.LocalChannel] = {}
        self._loop_refs: List[Any] = []
        self._actor_info: Dict[str, dict] = {}

        # ---- stage actors (dp x S)
        import uuid

        # fold a per-trainer token into the collective group names: two
        # concurrently-live trainers with the default name must not meet
        # in rendezvous (they would cross-average unrelated models)
        token = uuid.uuid4().hex[:8]
        cls = _stage_actor()
        opts = list(stage_options or [])
        self._actors: List[List[Any]] = []
        for r in range(self._dp):
            row = []
            for s, spec in enumerate(self._specs):
                acls = cls.options(**opts[s]) if s < len(opts) and opts[s] \
                    else cls
                row.append(acls.remote(
                    spec, s, self._S, self._M, optimizer, self._dp, r,
                    f"{name}.{token}.stage{s}"))
            self._actors.append(row)
        import ray_tpu

        ray_tpu.get([a.ping.remote() for row in self._actors for a in row],
                    timeout=120)

        if mode == "channels":
            try:
                self._build_channels()
            except BaseException:
                try:
                    self.shutdown()
                except Exception:
                    logger.debug("pipeline build unwind failed",
                                 exc_info=True)
                raise

    # -- properties the microbenchmark guard keys on

    @property
    def is_channel_backed(self) -> bool:
        return self._mode == "channels" and bool(self._all_specs)

    @property
    def channel_depth(self) -> int:
        return self._depth if self.is_channel_backed else 0

    @property
    def num_stages(self) -> int:
        return self._S

    # -- build

    def _create_channel(self, node_addr, n_readers, participants, *,
                        depth: Optional[int] = None,
                        buffer: Optional[int] = None
                        ) -> _channels.ChannelSpec:
        core = self._core
        spec = _channels.create_channel(
            core, node_addr, buffer or self._buffer,
            depth or self._depth, n_readers, participants)
        self._all_specs.append(spec)
        if tuple(node_addr) == tuple(core.supervisor_addr):
            self._local_channels[spec.key()] = _channels.LocalChannel(
                core.arena, spec)
        return spec

    def _build_channels(self) -> None:
        core = self._core
        driver_node = tuple(core.supervisor_addr)
        if core.arena is None:
            raise RuntimeError(
                "pipeline channels need a driver attached to a node arena")

        # resolve every stage actor's placement (one cluster-view
        # snapshot for the whole dp x S pass; actors don't migrate
        # between the per-actor ALIVE waits and channel creation)
        views = core._run(core.clients.get(core.controller_addr).call(
            "node_views"))
        for row in self._actors:
            for a in row:
                hexid = a._actor_id.hex()
                self._actor_info[hexid] = \
                    _channels.resolve_actor_placement(
                        core, a._actor_id, views)

        # ANY participant's death closes every channel of the trainer:
        # stages are serially dependent and dp replicas meet at the
        # flush allreduce, so no subset can make progress alone
        participants = {core._store_client_id}
        for info in self._actor_info.values():
            participants.add(info["worker_id_hex"])
            participants.add(f"node:{info['node_id_hex']}")

        def node_of(r, s):
            return self._actor_info[
                self._actors[r][s]._actor_id.hex()]["node_addr"]

        self._in_specs, self._label_specs = [], []
        self._report_readers: List[List[_channels.LocalChannel]] = []
        plans: List[List[_StagePlan]] = []
        for r in range(self._dp):
            in_spec = self._create_channel(node_of(r, 0), 1, participants)
            label_spec = self._create_channel(
                node_of(r, self._S - 1), 1, participants)
            act = [self._create_channel(node_of(r, s + 1), 1, participants)
                   for s in range(self._S - 1)]
            grad = [self._create_channel(node_of(r, s), 1, participants)
                    for s in range(self._S - 1)]
            # reports carry one small stats dict per flush, and the
            # driver acks flush t before scattering t+1 — depth 1 and a
            # small buffer, not S+1 slots of activation-sized pinned
            # arena each
            reports = [self._create_channel(driver_node, 1, participants,
                                            depth=1, buffer=64 * 1024)
                       for _ in range(self._S)]
            self._in_specs.append(in_spec)
            self._label_specs.append(label_spec)
            self._report_readers.append(
                [self._local_channels[sp.key()] for sp in reports])
            plans.append([_StagePlan(
                in_spec=in_spec if s == 0 else None,
                label_spec=label_spec if s == self._S - 1 else None,
                act_in=act[s - 1] if s > 0 else None,
                act_out=act[s] if s < self._S - 1 else None,
                grad_in=grad[s] if s < self._S - 1 else None,
                grad_out=grad[s - 1] if s > 0 else None,
                report=reports[s],
            ) for s in range(self._S)])

        # driver-side input writers (local write or mirror push)
        def driver_writer(spec):
            if tuple(spec.node_addr) == driver_node:
                return ("local", self._local_channels[spec.key()])
            return ("mirror", _channels.MirrorWriter(core, spec))

        self._in_writers = [driver_writer(sp) for sp in self._in_specs]
        self._label_writers = [driver_writer(sp) for sp in self._label_specs]

        # participant death -> close everything so nobody hangs
        for hexid in self._actor_info:
            core.subscribe("actor:" + hexid, self._on_actor_update)

        # start the run loops (they dedicate the actors until teardown)
        for r in range(self._dp):
            for s in range(self._S):
                self._loop_refs.append(
                    self._actors[r][s].run_loop.remote(plans[r][s]))

    # -- failure fan-out (same shape as dag._ChannelGraph)

    def _on_actor_update(self, message) -> None:
        if self._dead or not isinstance(message, dict):
            return
        if message.get("state") in ("DEAD", "RESTARTING"):
            self._close_for_failure()

    def _close_for_failure(self) -> None:
        """Close the whole pipeline (same lightweight fan-out as actor
        death): used when a step failed partway through its microbatch
        scatter — some channels carry the version, others never will, so
        a retried step would train on a MIX of two batches."""
        self._dead = True
        _channels.close_channels_nowait(
            self._core, self._local_channels.values(), self._all_specs)

    def _surface_failure(self, closed: ChannelClosedError):
        # a ChannelClosedError may wrap a TRANSPORT failure (a mirror
        # push that timed out against a still-healthy remote) — close
        # everything first so no stage loop stays parked on a version
        # that will never be written (CompiledDAG.execute's rule)
        self._close_for_failure()
        _channels.surface_loop_failure(self._core, self._loop_refs, closed)

    # -- stepping

    def _split(self, batch) -> List[List[np.ndarray]]:
        if isinstance(batch, dict):
            extra = set(batch) - {"tokens"}
            if extra:
                # dropping keys silently (e.g. a loss_fn-style 'mask')
                # would train on different math than the user believes
                raise ValueError(
                    f"PipelineTrainer batches support only {{'tokens'}}; "
                    f"got extra keys {sorted(extra)} (masking is not "
                    f"threaded through the stage loss yet)")
            tokens = batch["tokens"]
        else:
            tokens = batch
        tokens = np.asarray(tokens)
        B = tokens.shape[0]
        per = self._dp * self._M
        if B % per != 0:
            raise ValueError(
                f"batch size {B} not divisible by dp*num_microbatches "
                f"({self._dp}x{self._M})")
        mb = B // per
        return [[tokens[(r * self._M + m) * mb:(r * self._M + m + 1) * mb]
                 for m in range(self._M)] for r in range(self._dp)]

    def step(self, batch) -> Dict[str, Any]:
        """One optimizer step: scatter M microbatches per dp replica into
        the pipeline, collect every stage's flush report, return the mean
        loss. Steady-state cost: channel writes/reads only."""
        if self._mode == "tasks":
            return self._step_tasks(batch)
        if self._dead:
            raise ChannelClosedError("pipeline trainer was torn down")
        mbs = self._split(batch)
        vbase = 2 * (self._flush * self._M + 1)
        wrote = False
        try:
            for r in range(self._dp):
                for m, mb in enumerate(mbs[r]):
                    payload = serialization.pack(np.ascontiguousarray(mb))
                    v = vbase + 2 * m
                    for kind, w in (self._in_writers[r],
                                    self._label_writers[r]):
                        if kind == "local":
                            w.write(payload, v)
                        else:
                            w.push(payload, v)
                        wrote = True
        except ChannelClosedError as e:
            self._surface_failure(e)
        except BaseException:
            if wrote:
                # a partial scatter is unrecoverable: stage 0 already
                # acked some of this flush's microbatches, so a retried
                # step() would silently mix two batches into one
                # gradient — close the pipeline instead (same rule as
                # CompiledDAG.execute)
                self._close_for_failure()
            raise
        rv = 2 * (self._flush + 1)
        reports: List[dict] = []
        try:
            for r in range(self._dp):
                for ch in self._report_readers[r]:
                    view = ch.read(rv)
                    rep = serialization.unpack(bytes(view))
                    del view
                    ch.ack(0, rv)
                    rep["dp_rank"] = r
                    reports.append(rep)
        except ChannelClosedError as e:
            self._surface_failure(e)
        self._flush += 1
        last = [rep for rep in reports if rep["stage"] == self._S - 1]
        loss = float(np.mean([rep["loss_sum"] / rep["microbatches"]
                              for rep in last]))
        return {"loss": loss, "step": self._flush, "reports": reports}

    # -- dynamic task-per-stage baseline (object-store data plane)

    def _step_tasks(self, batch) -> Dict[str, Any]:
        import ray_tpu

        mbs = self._split(batch)
        barriers, loss_refs = [], []
        for r in range(self._dp):
            row = self._actors[r]
            for m, mb in enumerate(mbs[r]):
                ref = row[0].naive_fwd.remote(m, mb)
                for s in range(1, self._S - 1):
                    ref = row[s].naive_fwd.remote(m, ref)
                gref = row[self._S - 1].naive_loss_bwd.remote(m, ref, mb)
                for s in range(self._S - 2, -1, -1):
                    gref = row[s].naive_bwd.remote(m, gref)
                barriers.append(gref)
        ray_tpu.get(barriers, timeout=600)
        stats = ray_tpu.get(
            [a.naive_flush.remote() for row in self._actors for a in row],
            timeout=600)
        self._flush += 1
        last = stats[self._S - 1::self._S]
        loss = float(np.mean([st["loss_sum"] / st["microbatches"]
                              for st in last]))
        return {"loss": loss, "step": self._flush, "reports": stats}

    # -- introspection / teardown

    def fetch_params(self, stage: int, dp_rank: int = 0):
        """Stage shard params (tasks mode anytime; channels mode after
        shutdown — the run loop dedicates the actor)."""
        import ray_tpu

        return ray_tpu.get(
            self._actors[dp_rank][stage].fetch_params.remote(), timeout=120)

    def shutdown(self, kill_actors: bool = True,
                 timeout: float = 30) -> Dict[str, Any]:
        """Close every channel, stop the stage loops, release the pins,
        (optionally) kill the stage actors. Idempotent."""
        self._dead = True
        # only the FIRST call may run the release: after it frees the
        # channel ranges they can be recycled to a NEWER trainer/graph,
        # and a repeat close (e.g. __del__ racing an explicit shutdown
        # from another thread) would stamp the closed flag into live
        # channels that aren't ours anymore (the dag teardown rule)
        with self._teardown_lock:
            if self._torn:
                return {}
            self._torn = True
        core = self._core
        for ch in self._local_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        for hexid in self._actor_info:
            try:
                core.unsubscribe("actor:" + hexid, self._on_actor_update)
            except Exception:
                pass

        _channels.close_specs(core, self._all_specs)
        stats: Dict[str, Any] = {"loops": []}
        for ref in self._loop_refs:
            try:
                stats["loops"].append(core.get([ref], timeout=timeout)[0])
            except Exception:
                stats["loops"].append(None)
        _channels.free_and_unpin_specs(core, self._all_specs)
        if kill_actors:
            import ray_tpu

            for row in self._actors:
                for a in row:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass
        return stats

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
