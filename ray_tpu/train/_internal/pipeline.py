"""MPMD pipeline-parallel training: interleaved 1F1B over channels.

Reproduces the topology of "Scaling Deep Learning Training with MPMD
Pipeline Parallelism" (arXiv:2412.14374) on this framework's fast-path
substrate: S stage actors own model shards, forward activations and
backward gradients flow stage-to-stage through compiled-graph channels
(`_private/channels.py` — pin-backed seqlock slot rings, NOT the object
store), and each stage's long-running run loop executes an EAGER 1F1B
microbatch schedule: backward as soon as its gradient is committed
(gradients still accumulate per chunk in microbatch order, so numerics
are deterministic), otherwise forwards ahead bounded by the channel
depth. Optional intra-stage data parallelism rides the p2p collective
layer: dp replicas of every stage sync their accumulated gradients with
one ``allreduce_coalesced_async(op=MEAN)`` at flush.

Interleaved virtual stages (``virtual_stages=V`` > 1): each stage actor
owns V NON-CONTIGUOUS model chunks — stage s owns chunks s, s+S, s+2S,
... of the S*V-chunk pipeline — and the channel plan grows per-chunk
act/grad edges between the SAME S actors (the existing depth-k slot
rings; no new protocol). The 1F1B bubble scales as (S-1)/(V*M) instead
of (S-1)/M: while a one-chunk stage idles waiting for the pipeline to
fill or drain, an interleaved stage has V-1 other chunks' microbatches
to run. V=1 executes the PR-8 schedule byte-for-byte (same code path).

Fused in-bucket optimizer (``fused_flush``, default on, dp > 1): the
flush's coalesced-mean allreduce carries an ``on_bucket`` completion
callback, and each stage applies a JITTED per-bucket optax update
(against pre-split per-bucket opt state) the moment that bucket's
reduce lands — overlapped with the remaining buckets' device_get +
reduce rounds — instead of waiting for the full tree and unpacking
through host numpy. Per-bucket apply is exact for leafwise optimizers
(sgd/adam families); pass ``fused_flush=False`` for optimizers with
cross-leaf state (e.g. ``optax.clip_by_global_norm`` chains), which is
also the measured unfused baseline.

The steady-state cost model is the whole point: one microbatch hop is a
channel write + a channel read (same-node: two shared-memory seqlock
ops; cross-node: one pre-established push over the chunked transfer
window). A steady flush issues ZERO control-plane RPCs per stage rank —
counter-proven via ``ray_tpu_rpc_client_calls_total`` deltas carried in
each stage's per-flush report. Contrast `parallel/pipeline.py`, the
SPMD-inside-one-jit GPipe over a `pp` mesh axis: that recipe needs every
stage on one jit-reachable mesh; this one composes independent
processes/hosts, which is what the MPMD paper is about.

Channel depth: 1F1B needs capacity for several in-flight microbatches
per edge, so the trainer compiles its channels at depth
``max(2, min(S*V + 1, M))`` by default (the PR-8 slot ring). Depth 1
would still be deadlock-free — the schedule degenerates to lockstep —
but serializes the pipeline; the microbenchmark guard asserts depth > 1
so an accidental fallback can't vacuously pass.

Failure semantics match compiled DAGs: teardown or any participant's
death closes every channel (supervisor participant registry + a
driver-side actor-state subscription), blocked peers raise
``ChannelClosedError`` instead of hanging, and the per-flush gradient
state is discarded — a broken pipeline can produce an error, never a
wrong loss.

``mode="tasks"`` runs the SAME chunk math as dynamic actor tasks through
the object store (per-microbatch per-chunk `.remote()` calls) — the
baseline `pipeline_task_per_stage_step` microbenchmark probe and a
debugging aid, not a fallback: channel compilation failures raise.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu._private import channels as _channels
from ray_tpu._private import chaos, flight, serialization
from ray_tpu._private.exceptions import ChannelClosedError
from ray_tpu._private.metrics import Counter, Gauge, Histogram

logger = logging.getLogger(__name__)

# flight-recorder span ids (per-thread ring records, zero RPCs): the
# per-microbatch phases the aggregate bubble gauge can't localize
_F_FWD = flight.intern("pipe.fwd")
_F_BWD = flight.intern("pipe.bwd")
_F_FLUSH = flight.intern("pipe.flush")
_F_OPT = flight.intern("pipe.opt")
_F_DP = flight.intern("pipe.dp_allreduce")
_F_BUBBLE = flight.intern("pipe.bubble_bp")
_F_TPTAIL = flight.intern("pipe.tp_tail_wait")

_m_microbatches = Counter(
    "ray_tpu_pipeline_microbatches_total",
    "Pipeline chunk-microbatches processed (M per chunk per flush, so "
    "M*V per flush at virtual_stages=V), by stage rank")
_m_flushes = Counter(
    "ray_tpu_pipeline_flushes_total",
    "Pipeline flushes (optimizer steps) completed, by stage rank")
_m_stage_seconds = Histogram(
    "ray_tpu_pipeline_stage_step_seconds",
    "Per-stage wall seconds for one flush (M microbatches + optimizer)")
_m_bubble = Gauge(
    "ray_tpu_pipeline_bubble_fraction",
    "Fraction of the last flush a stage spent blocked on channel "
    "waits (the pipeline bubble, measured not estimated)")
_m_fused_applies = Counter(
    "ray_tpu_pipeline_fused_bucket_applies_total",
    "Fused in-bucket optimizer applies (one jitted update per landed "
    "allreduce bucket, overlapped with the remaining buckets' rounds), "
    "by stage rank")


@dataclasses.dataclass
class StageSpec:
    """One pipeline chunk's model shard as pure, PICKLABLE callables
    (module-level functions / functools.partial — they ship to the stage
    actor). Chunks 0..C-2 define ``fwd``; the last chunk defines
    ``loss``.

      init()                  -> params pytree (this shard only)
      fwd(params, x)          -> y activations (differentiable in both)
      loss(params, x, labels) -> scalar loss (differentiable in p and x)

    Tensor-parallel chunks (``tp`` > 1, e.g. from
    ``pipeline_stage_defs(cfg, S, tensor_parallel=tp)``) additionally
    accept ``init(tp_rank=...)`` (this rank's Megatron column/row shard)
    and ``fwd/loss(..., tp_ops=(g, f))`` — the partial-sum reduce pair
    the trainer binds to this rank's per-(stage, dp) tp group. With
    ``tp_tail`` the fwd returns the last block's ``(u, mlp_partial)``
    pair instead of the finished activation; the run loop completes
    ``u + allreduce(mlp_partial)`` on the host, asynchronously when the
    next scheduled op allows overlap.
    """

    init: Callable[[], Any]
    fwd: Optional[Callable[[Any, Any], Any]] = None
    loss: Optional[Callable[[Any, Any, Any], Any]] = None
    tp: int = 1
    tp_tail: bool = False


def _as_stage_spec(obj) -> StageSpec:
    if isinstance(obj, StageSpec):
        return obj
    if isinstance(obj, dict):
        return StageSpec(init=obj["init"], fwd=obj.get("fwd"),
                         loss=obj.get("loss"), tp=int(obj.get("tp", 1)),
                         tp_tail=bool(obj.get("tp_tail", False)))
    raise TypeError(f"not a stage spec: {obj!r}")


@dataclasses.dataclass
class _StagePlan:
    """Wire-shippable channel plan for one stage actor's run loop. The
    act/grad entries are PER LOCAL CHUNK (index v, global chunk
    s + v*S): act_in[v] is None for global chunk 0 (which reads
    ``in_spec``), act_out[v]/grad_in[v] are None for the last global
    chunk (loss — nothing downstream), grad_out[v] is None for global
    chunk 0 (raw data upstream). At virtual_stages=1 every list is one
    entry and the plan is exactly the PR-8 shape."""

    in_spec: Optional[_channels.ChannelSpec]  # driver -> stage 0
    label_spec: Optional[_channels.ChannelSpec]  # driver -> last stage
    act_in: List[Optional[_channels.ChannelSpec]]  # chunk c-1 -> c
    act_out: List[Optional[_channels.ChannelSpec]]  # chunk c -> c+1
    grad_in: List[Optional[_channels.ChannelSpec]]  # chunk c+1 -> c
    grad_out: List[Optional[_channels.ChannelSpec]]  # chunk c -> c-1
    report: _channels.ChannelSpec  # stage s -> driver, one per flush


# --------------------------------------------------------------- stage math


class _ChunkRuntime:
    """One model chunk's compute state: the shard params, jitted fwd/bwd
    (bwd recomputes the chunk forward from the stashed INPUT activation
    — full-remat 1F1B, so the stash is one input per in-flight
    microbatch, never the whole residual tree), gradient accumulator."""

    def __init__(self, spec: StageSpec, chunk: int, num_chunks: int,
                 tp_rank: int = 0, tp_ops=None):
        import functools

        import jax

        self.spec = spec
        self.chunk = int(chunk)
        self.first = self.chunk == 0
        self.last = self.chunk == int(num_chunks) - 1
        self.tp = int(getattr(spec, "tp", 1) or 1)
        # tail chunks end on the last block's (u, mlp_partial) pair —
        # the run loop completes u + allreduce(mp) on the host so the
        # reduce can overlap the NEXT microbatch's compute
        self.tail = bool(spec.tp_tail) and self.tp > 1 and not self.last
        if self.tp > 1:
            # bind this rank's shard + the trainer's reduce pair into
            # the spec callables: downstream code sees plain fns
            init_fn = functools.partial(spec.init, tp_rank=int(tp_rank))
            fwd_fn = (functools.partial(spec.fwd, tp_ops=tp_ops)
                      if spec.fwd is not None else None)
            loss_fn = (functools.partial(spec.loss, tp_ops=tp_ops)
                       if spec.loss is not None else None)
        else:
            init_fn, fwd_fn, loss_fn = spec.init, spec.fwd, spec.loss
        self.params = init_fn()
        self._stash: Dict[int, Any] = {}
        self.acc = None
        self.losses: List[float] = []

        def tree_add(a, b):
            return jax.tree.map(lambda x, y: x + y, a, b)

        # The gradient ACCUMULATION is fused into the backward jit (one
        # dispatch per microbatch, XLA folds the add into the vjp) with
        # the running accumulator donated in place. Two variants each:
        # the flush's first microbatch has no accumulator yet.
        if self.last:
            if loss_fn is None:
                raise ValueError(
                    f"chunk {chunk} is the last of {num_chunks} and needs "
                    f"a loss callable")
            lg = jax.value_and_grad(loss_fn, argnums=(0, 1))

            def _lg_first(p, x, labels):
                loss, (gp, gx) = lg(p, x, labels)
                return loss, gx, gp

            def _lg_acc(p, x, labels, acc):
                loss, (gp, gx) = lg(p, x, labels)
                return loss, gx, tree_add(acc, gp)

            self._lg_first = jax.jit(_lg_first)
            self._lg_acc = jax.jit(_lg_acc, donate_argnums=3)
        else:
            if fwd_fn is None:
                raise ValueError(f"chunk {chunk} needs a fwd callable")
            self._fwd = jax.jit(fwd_fn)
            fwd = fwd_fn
            # tail chunks emit (u, mp) with y = u + allreduce(mp)
            # completed OUTSIDE the jit: dy/du is the identity and the
            # partial-sum allreduce is identity in its backward (the g
            # rule), so the downstream cotangent gy enters BOTH outputs
            tail = self.tail

            def cot(gy):
                return (gy, gy) if tail else gy

            if self.first:
                # input is raw data (tokens): no gradient flows past it
                def _bwd_first(p, x, gy):
                    _, vjp = jax.vjp(lambda pp: fwd(pp, x), p)
                    (gp,) = vjp(cot(gy))
                    return None, gp

                def _bwd_acc(p, x, gy, acc):
                    _, vjp = jax.vjp(lambda pp: fwd(pp, x), p)
                    (gp,) = vjp(cot(gy))
                    return None, tree_add(acc, gp)
            else:
                def _bwd_first(p, x, gy):
                    _, vjp = jax.vjp(fwd, p, x)
                    gp, gx = vjp(cot(gy))
                    return gx, gp

                def _bwd_acc(p, x, gy, acc):
                    _, vjp = jax.vjp(fwd, p, x)
                    gp, gx = vjp(cot(gy))
                    return gx, tree_add(acc, gp)
            self._bwd_first = jax.jit(_bwd_first)
            self._bwd_acc = jax.jit(_bwd_acc, donate_argnums=3)

    def forward(self, m: int, x) -> Any:
        """Non-last chunks: y = fwd(params, x); stash x for the backward
        recompute."""
        y = self._fwd(self.params, x)
        self._stash[m] = x
        return y

    def loss_backward(self, x, labels) -> Tuple[float, Any]:
        """Last chunk only: loss + grads (+ fused accumulation) in one
        jit call (fwd and bwd of the last chunk are adjacent in 1F1B, so
        there is nothing to stash)."""
        if self.acc is None:
            loss, gx, self.acc = self._lg_first(self.params, x, labels)
        else:
            loss, gx, self.acc = self._lg_acc(
                self.params, x, labels, self.acc)
        self.losses.append(float(loss))
        return float(loss), gx

    def backward(self, m: int, gy) -> Any:
        """Recompute this chunk's forward from the stashed input, apply
        the vjp, fold the param grads into the accumulator; returns the
        input gradient (None at chunk 0)."""
        x = self._stash.pop(m)
        if self.acc is None:
            gx, self.acc = self._bwd_first(self.params, x, gy)
        else:
            gx, self.acc = self._bwd_acc(self.params, x, gy, self.acc)
        return gx


class _StageRuntime:
    """One stage actor's compute state: V chunk runtimes (local index v
    owns global chunk stage + v*S), the optimizer, and the flush."""

    def __init__(self, specs: Sequence[StageSpec], stage: int,
                 num_stages: int, virtual_stages: int,
                 num_microbatches: int, optimizer, dp: int, dp_rank: int,
                 group_name: str, fused_flush: bool = True,
                 flush_bucket_bytes: Optional[int] = None,
                 declarative_group: bool = False, tp: int = 1,
                 tp_rank: int = 0, tp_group: Optional[str] = None,
                 tp_tail_group: Optional[str] = None,
                 tp_overlap: bool = True):
        self.stage = int(stage)
        self.S = int(num_stages)
        self.V = int(virtual_stages)
        self.M = int(num_microbatches)
        self.dp = int(dp)
        self.dp_rank = int(dp_rank)
        self.group_name = group_name
        # elastic trainers declare the dp group driver-side
        # (util.collective.create_collective_group): members resolve
        # their rank lazily on the first op and re-rendezvous at the new
        # generation after a resize — no imperative init here
        self._declarative = bool(declarative_group)
        self._group_ready = False
        # ---- tensor parallelism (tp x dp x pp): this rank holds each
        # chunk's 1/tp Megatron column/row shard; the in-jit partial-sum
        # reduces go through a pure_callback pair bound here against the
        # per-(stage, dp-rank) tp group. The callbacks carry no tags —
        # EXECUTION ORDER IS THE MATCH — which is why tp > 1 runs the
        # deterministic static schedule (run_flush_tp), never the
        # timing-dependent ready()-probing loops.
        self.tp = int(tp)
        self.tp_rank = int(tp_rank)
        self.tp_group = tp_group
        self.tp_tail_group = tp_tail_group
        self.tp_overlap = bool(tp_overlap)
        self._tp_reduce_calls = 0  # lifetime; reports carry deltas
        tp_ops = None
        if self.tp > 1:
            if not tp_group or not tp_tail_group:
                raise ValueError(
                    f"stage {stage}: tp={tp} needs tp_group and "
                    f"tp_tail_group collective group names")
            from ray_tpu.util.collective.tp import make_tp_reduce_ops

            def _tp_reduce(arr):
                from ray_tpu.util import collective as col
                from ray_tpu.util.collective.types import ReduceOp

                self._tp_reduce_calls += 1
                return col.allreduce(arr, group_name=self.tp_group,
                                     op=ReduceOp.SUM)

            tp_ops = make_tp_reduce_ops(_tp_reduce)
        C = self.S * self.V
        self.chunks = [
            _ChunkRuntime(spec, self.stage + v * self.S, C,
                          tp_rank=self.tp_rank, tp_ops=tp_ops)
            for v, spec in enumerate(specs)]
        self.first = self.chunks[0].first  # global chunk 0 lives here
        self.last = self.chunks[-1].last  # the loss chunk lives here
        self._optimizer = optimizer
        self._fused = bool(fused_flush)
        self._bucket_bytes = flush_bucket_bytes
        self._opt = None
        self._opt_state = None
        self._update = None
        self._fused_buckets: Optional[Dict[tuple, dict]] = None
        self._fused_applies = 0  # lifetime count; reports carry deltas

    # -- per-microbatch (chunk-indexed)

    def forward(self, v: int, m: int, x) -> Any:
        return self.chunks[v].forward(m, x)

    def loss_backward(self, v: int, x, labels) -> Tuple[float, Any]:
        return self.chunks[v].loss_backward(x, labels)

    def backward(self, v: int, m: int, gy) -> Any:
        return self.chunks[v].backward(m, gy)

    # -- tail reduce (tp > 1): the last block's mlp partial sum rides a
    # SEPARATE collective group from the in-jit callbacks, so a pending
    # async tail reduce can never be mis-paired with the next
    # microbatch's in-jit reduce sequence

    def tail_reduce_async(self, mp):
        """Kick the tail partial's allreduce on the runner thread and
        return the CollectiveWork handle — the caller overlaps it with
        the next microbatch's forward compute."""
        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        self._tp_reduce_calls += 1
        return col.allreduce_coalesced_async(
            [np.asarray(mp)], group_name=self.tp_tail_group,
            op=ReduceOp.SUM)

    def tail_combine(self, u, work, timeout_ms: int = 120_000):
        """Finish y = u + allreduce(mp): wait for the tail reduce and
        add the (replicated-exact) sum onto the residual stream."""
        (reduced,) = work.wait(timeout_ms)
        return np.asarray(u) + reduced

    # -- flush

    def _ensure_group(self) -> None:
        if self._declarative:
            # driver-declared group: ops resolve membership from the
            # declarative KV record (current generation) on demand
            return
        if self.dp > 1 and not self._group_ready:
            from ray_tpu.util import collective as col

            col.init_collective_group(
                self.dp, self.dp_rank, backend="host",
                group_name=self.group_name)
            self._group_ready = True

    def _make_opt(self):
        import optax

        if callable(self._optimizer):
            return self._optimizer()
        kind, lr = self._optimizer
        if kind != "sgd":
            raise ValueError(f"unknown optimizer {kind!r}")
        return optax.sgd(lr)

    def _ensure_opt(self) -> None:
        if self._opt is not None:
            return
        import jax
        import optax

        opt = self._make_opt()
        self._opt = opt
        params = tuple(ck.params for ck in self.chunks)
        self._opt_state = opt.init(params)

        def update(params, opt_state, grads):
            updates, new_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state

        self._update = jax.jit(update)

    def _resolved_bucket_bytes(self) -> int:
        if self._bucket_bytes is not None:
            return int(self._bucket_bytes)
        from ray_tpu.util.collective.collective import _default_bucket_bytes

        return _default_bucket_bytes()

    def _ensure_fused_opt(self, grad_leaves: List[Any]) -> None:
        """Pre-split the optimizer per coalesced bucket: the layout is a
        pure function of the (fixed) gradient tree + bucket size, so
        this runs once — one optax instance + opt state + jitted apply
        per bucket, each over just that bucket's param leaves."""
        if self._fused_buckets is not None:
            return
        import jax
        import optax

        from ray_tpu.util.collective.async_work import bucket_layout

        params_leaves = jax.tree.leaves(
            tuple(ck.params for ck in self.chunks))
        buckets = bucket_layout(grad_leaves, self._resolved_bucket_bytes())
        table: Dict[tuple, dict] = {}
        for bucket in buckets:
            opt = self._make_opt()
            plist = [params_leaves[i] for i in bucket]

            def update(params_list, opt_state, grads_list, _opt=opt):
                updates, new_state = _opt.update(
                    grads_list, opt_state, params_list)
                return optax.apply_updates(params_list, updates), new_state

            table[tuple(bucket)] = {
                "state": opt.init(plist),
                "update": jax.jit(update),
            }
        self._fused_buckets = table

    def _fused_reduce_apply(self, leaves: List[Any],
                            timeout_ms: int) -> List[Any]:
        """dp allreduce with the optimizer FUSED into the buckets: the
        per-bucket completion callback hands each landed bucket to a
        dedicated apply thread, which dispatches that bucket's jitted
        optax apply while the runner reduces the remaining buckets — so
        the full-tree wait + host-numpy unpack + whole-tree update
        round-trip is gone. The handoff is a queue put, NOT the apply
        itself: the callback runs on the collective reducer thread,
        which is in lockstep with the peer ranks' rounds — running the
        apply there would serialize it into EVERY rank's reduce
        critical path. Returns the new param leaves (grad-leaf
        order)."""
        import queue as _queue

        import jax

        from ray_tpu.util import collective as col
        from ray_tpu.util.collective.types import ReduceOp

        self._ensure_fused_opt(leaves)
        params_leaves = jax.tree.leaves(
            tuple(ck.params for ck in self.chunks))
        new_leaves: List[Any] = [None] * len(leaves)
        table = self._fused_buckets
        stage_label = {"stage": str(self.stage)}
        handoff: "_queue.Queue" = _queue.Queue()

        def on_bucket(indices, arrays):
            # arrays are the runner's fresh per-bucket copies (no out=),
            # safe to hand across threads
            handoff.put((list(indices), list(arrays)))

        work = col.allreduce_coalesced_async(
            leaves, group_name=self.group_name, op=ReduceOp.MEAN,
            timeout_ms=timeout_ms,
            bucket_bytes=self._resolved_bucket_bytes(),
            on_bucket=on_bucket)
        # Drain + apply ON THIS THREAD, which would otherwise park in
        # wait(): each landed bucket's jitted apply runs while the
        # runner reduces the remaining buckets. The callback itself only
        # enqueues — it fires on the collective reducer thread, which is
        # in lockstep with the peer ranks' rounds, so running the apply
        # there would serialize it into EVERY rank's reduce critical
        # path.
        deadline = time.monotonic() + timeout_ms / 1000.0
        applied = 0
        while applied < len(table):
            try:
                indices, arrays = handoff.get(timeout=0.05)
            except _queue.Empty:
                if work.done() and work.exception() is not None:
                    raise work.exception()
                if time.monotonic() > deadline:
                    work.wait(0)  # surfaces the collective's own error
                    raise TimeoutError(
                        f"stage {self.stage}: fused flush timed out with "
                        f"{len(table) - applied} buckets unapplied")
                continue
            entry = table[tuple(indices)]
            plist = [params_leaves[i] for i in indices]
            upd, entry["state"] = entry["update"](
                plist, entry["state"], arrays)
            for i, p in zip(indices, upd):
                new_leaves[i] = p
            applied += 1
            self._fused_applies += 1
            _m_fused_applies.inc(labels=stage_label)
        work.wait(timeout_ms)  # instant: every bucket already landed
        if any(p is None for p in new_leaves):
            raise RuntimeError(
                "fused flush finished with unapplied buckets "
                "(bucket-layout mismatch between ranks?)")
        return new_leaves

    def flush(self, timeout_ms: int = 120_000) -> Dict[str, Any]:
        """Average the accumulated grads over M microbatches (and the dp
        replica group when dp > 1), apply the optimizer, reset."""
        import jax

        applies_before = self._fused_applies
        for ck in self.chunks:
            if ck._stash:
                raise RuntimeError(
                    f"stage {self.stage} chunk {ck.chunk}: flush with "
                    f"{len(ck._stash)} unconsumed activation stashes "
                    f"(schedule bug)")
            if ck.acc is None:
                raise RuntimeError(
                    f"stage {self.stage} chunk {ck.chunk}: flush with "
                    f"no grads")
        grads = tuple(ck.acc for ck in self.chunks)
        for ck in self.chunks:
            ck.acc = None
        scale = 1.0 / self.M
        grads = jax.tree.map(lambda g: g * scale, grads)
        if self.dp > 1:
            from ray_tpu.util import collective as col
            from ray_tpu.util.collective.types import ReduceOp

            self._ensure_group()
            leaves, treedef = jax.tree.flatten(grads)
            t0 = flight.now()
            if self._fused:
                new_leaves = self._fused_reduce_apply(leaves, timeout_ms)
                flight.span_since(_F_DP, t0)
                new_params = jax.tree.unflatten(treedef, new_leaves)
                for ck, p in zip(self.chunks, new_params):
                    ck.params = p
                return self._flush_stats(applies_before)
            # same bucket granularity as the fused path, so the two
            # flush modes differ ONLY in where the optimizer runs
            work = col.allreduce_coalesced_async(
                leaves, group_name=self.group_name, op=ReduceOp.MEAN,
                timeout_ms=timeout_ms,
                bucket_bytes=self._resolved_bucket_bytes())
            reduced = work.wait(timeout_ms)
            flight.span_since(_F_DP, t0)
            grads = jax.tree.unflatten(treedef, reduced)
        self._ensure_opt()
        params = tuple(ck.params for ck in self.chunks)
        new_params, self._opt_state = self._update(
            params, self._opt_state, grads)
        for ck, p in zip(self.chunks, new_params):
            ck.params = p
        return self._flush_stats(applies_before)

    def _flush_stats(self, applies_before: int) -> Dict[str, Any]:
        losses: List[float] = []
        for ck in self.chunks:
            losses.extend(ck.losses)
            ck.losses = []
        return {"loss_sum": float(np.sum(losses)) if losses else 0.0,
                "microbatches": self.M,
                "fused_bucket_applies":
                    self._fused_applies - applies_before}

    # -- elastic membership (driver-orchestrated, between flushes)

    def reset_group(self, dp: int, dp_rank: int) -> None:
        """Adopt a resized dp group: the driver re-declared it at a new
        generation; drop this member's stale cached rendezvous so the
        next collective call (the rejoin sync or the next flush) joins
        the new world. The MEAN scale of the flush allreduce re-derives
        from the live world size by construction."""
        from ray_tpu.util.collective.resizable import refresh_membership

        self.dp = int(dp)
        self.dp_rank = int(dp_rank)
        refresh_membership(self.group_name)

    def sync_state(self, src_rank: int, timeout_ms: int) -> str:
        """One leaf-wise param/optimizer broadcast over the (resized) dp
        group: ``src_rank`` sends its live tree, everyone else installs
        the received copy — the joiner's no-checkpoint rejoin path, and
        a re-anchor for survivors whose mid-flush state may have
        diverged (partial fused-bucket applies on a torn round)."""
        from ray_tpu.util.collective.resizable import sync_tree

        state = None
        if self.dp_rank == src_rank:
            state = {
                "params": [ck.params for ck in self.chunks],
                "opt": self._opt_state,
                "fused": (
                    {k: e["state"] for k, e in self._fused_buckets.items()}
                    if self._fused_buckets is not None else None),
            }
        synced = sync_tree(state, self.group_name, src_rank=src_rank,
                           timeout_ms=timeout_ms)
        if self.dp_rank != src_rank:
            self._install_state(synced)
        return "ok"

    def _install_state(self, state: Dict[str, Any]) -> None:
        import jax

        for ck, p in zip(self.chunks, state["params"]):
            ck.params = p
        if state["opt"] is not None:
            self._ensure_opt()
            self._opt_state = state["opt"]
        if state["fused"] is not None:
            # the bucket layout is a pure function of the (identical)
            # param tree + bucket bytes, so the sender's keys match ours
            self._ensure_fused_opt(jax.tree.leaves(
                tuple(ck.params for ck in self.chunks)))
            for key, st in state["fused"].items():
                if tuple(key) not in self._fused_buckets:
                    raise RuntimeError(
                        f"stage {self.stage}: synced fused-opt bucket "
                        f"{key!r} has no local counterpart (bucket-layout "
                        f"drift between dp ranks?)")
                self._fused_buckets[tuple(key)]["state"] = st


# ----------------------------------------------------- worker-side run loop


# version-addressed local-or-mirror channel writer, shared with the
# compiled-DAG and podracer layers (_private/channels.py)
_Writer = _channels.VersionedWriter


def _copy_tree(value):
    """Deep-copy ndarray leaves out of the shared arena so the channel
    can be acked (and the writer may overwrite) while the value lives
    on."""
    if isinstance(value, np.ndarray):
        return np.array(value)
    if isinstance(value, dict):
        return {k: _copy_tree(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_copy_tree(v) for v in value)
    return value


def _simulate_tp_schedule(S: int, V: int, M: int, depth: int,
                          stage: int) -> List[Tuple[str, int, int]]:
    """Deterministic static 1F1B order for ONE stage of a tp > 1
    pipeline, as ``[("fwd" | "bwd", local_chunk_v, m), ...]``.

    Tensor parallelism forbids the dynamic schedulers: their
    ``ready()``-probing choices diverge with timing across tp peers, and
    the in-jit reduce callbacks carry no tags — a mismatched op sequence
    silently sums the WRONG microbatches (shapes match) or deadlocks. So
    every rank derives the same order from the same (S, V, M, depth,
    stage) inputs by simulating all S stages jointly with unit-time ops:

      - per tick each stage runs its deepest ready backward, else its
        shallowest ready forward (the measured-best interleaved policy);
      - the loss chunk is ONE fused fwd+bwd op (as in the real loop);
      - per-chunk in-flight stashes are bounded by min(M, depth) and
        each act/grad ring holds at most ``depth`` unread values
        (writes need space; reads ack immediately, like the run loop);
      - a value written at tick t is readable from t+1.

    The simulated global schedule is feasible under exactly the run
    loop's blocking-read/write semantics, so S loops each executing
    their own slice of it in order cannot deadlock: every op's inputs
    are produced by ops earlier in the witness order, and ring space for
    every write is freed by reads earlier in the witness order.
    """
    C = S * V
    limit = max(1, min(M, depth))
    fwd_done = [0] * C
    bwd_done = [0] * C
    act_occ = [0] * max(C - 1, 0)   # chunk c -> c+1 values in flight
    grad_occ = [0] * max(C - 1, 0)  # chunk c+1 -> c values in flight
    act_tick: Dict[Tuple[int, int], int] = {}   # (edge c, m) -> write tick
    grad_tick: Dict[Tuple[int, int], int] = {}
    order: List[List[Tuple[str, int, int]]] = [[] for _ in range(S)]
    total = 2 * C * M
    tick = 0
    while sum(fwd_done) + sum(bwd_done) < total:
        progressed = False
        for s in range(S):
            chunks = [s + u * S for u in range(V)]
            op = None
            # deepest ready backward first: it frees a stash slot and
            # feeds upstream soonest (loss chunk has no separate bwd)
            for c in reversed(chunks):
                if c == C - 1 or bwd_done[c] >= fwd_done[c]:
                    continue
                m = bwd_done[c]
                if grad_tick.get((c, m), tick) >= tick:
                    continue  # grad not committed before this tick
                if c > 0 and grad_occ[c - 1] >= depth:
                    continue  # no ring space for our grad write
                op = ("bwd", c, m)
                break
            if op is None:
                # shallowest ready forward (fills downstream soonest)
                for c in chunks:
                    if fwd_done[c] >= M:
                        continue
                    m = fwd_done[c]
                    if c > 0 and act_tick.get((c - 1, m), tick) >= tick:
                        continue  # input act not committed yet
                    if c == C - 1:
                        # loss chunk: fused fwd+bwd, writes grad C-2
                        if grad_occ[c - 1] >= depth:
                            continue
                        op = ("loss", c, m)
                        break
                    if fwd_done[c] - bwd_done[c] >= limit:
                        continue  # stash bound
                    if act_occ[c] >= depth:
                        continue  # no ring space for our act write
                    op = ("fwd", c, m)
                    break
            if op is None:
                continue
            kind, c, m = op
            progressed = True
            if kind == "bwd":
                bwd_done[c] += 1
                grad_occ[c] -= 1  # read acks the grad we consumed
                if c > 0:
                    grad_occ[c - 1] += 1
                    grad_tick[(c - 1, m)] = tick
                order[s].append(("bwd", c // S, m))
            elif kind == "loss":
                fwd_done[c] += 1
                bwd_done[c] += 1
                act_occ[c - 1] -= 1
                grad_occ[c - 1] += 1
                grad_tick[(c - 1, m)] = tick
                order[s].append(("fwd", c // S, m))
            else:
                fwd_done[c] += 1
                if c > 0:
                    act_occ[c - 1] -= 1
                if c < C - 1:
                    act_occ[c] += 1
                    act_tick[(c, m)] = tick
                order[s].append(("fwd", c // S, m))
        if not progressed:
            raise RuntimeError(
                f"tp schedule simulation wedged at tick {tick} "
                f"(S={S} V={V} M={M} depth={depth}; "
                f"fwd={fwd_done} bwd={bwd_done}) — scheduler bug")
        tick += 1
    return order[int(stage)]


def _run_stage_loop(rt: _StageRuntime, plan: _StagePlan) -> dict:
    """The per-actor eager-1F1B run loop (occupies the stage actor until
    its channels close): per flush, run backwards the moment their
    gradients are committed and forwards ahead up to the channel-depth
    in-flight bound — interleaving across this stage's V chunks when
    virtual_stages > 1 — then the optimizer flush and one report write.
    Steady flushes touch channels and local compute only — the per-flush
    report carries this rank's observed
    ``ray_tpu_rpc_client_calls_total`` delta as proof."""
    from ray_tpu._private import api, rpc

    core = api._core
    if core is None:
        raise RuntimeError("pipeline stage loop outside a worker process")

    open_local, local, release_pins = _channels.open_local_factory(core)

    def open_reader(spec) -> Optional[_channels.LocalChannel]:
        return open_local(spec) if spec is not None else None

    remote_specs: List[_channels.ChannelSpec] = []

    def writer(spec) -> Optional[_Writer]:
        if spec is None:
            return None
        w = _Writer(core, spec, open_local)
        if w._mirror is not None:
            remote_specs.append(spec)
        return w

    s, S, M, V = rt.stage, rt.S, rt.M, rt.V
    stage_label = {"stage": str(s)}
    try:
        in_ch = open_reader(plan.in_spec)
        label_ch = open_reader(plan.label_spec)
        act_in = [open_reader(sp) for sp in plan.act_in]
        grad_in = [open_reader(sp) for sp in plan.grad_in]
        act_out = [writer(sp) for sp in plan.act_out]
        grad_out = [writer(sp) for sp in plan.grad_out]
        report_w = writer(plan.report)
    except BaseException:
        release_pins()
        raise

    def close_everything() -> None:
        _channels.close_channels_nowait(core, local.values(), remote_specs)

    wait_box = [0.0]
    first_read = [False]  # True while waiting on the flush's FIRST read
    t_box = [0.0]

    def read_value(ch: _channels.LocalChannel, version: int):
        t0 = time.perf_counter()
        view = ch.read(version)
        if first_read[0]:
            # the wait for a flush's first input spans the driver's
            # think-time between step() calls — that's idle, not
            # pipeline bubble; start the flush clock here instead
            first_read[0] = False
            t_box[0] = time.perf_counter()
        else:
            wait_box[0] += time.perf_counter() - t0
        value = _copy_tree(serialization.unpack(view))
        del view
        ch.ack(0, version)
        return value

    def write_value(w: _Writer, value, version: int) -> None:
        payload = serialization.pack(np.asarray(value))
        t0 = time.perf_counter()
        w.write(payload, version)
        wait_box[0] += time.perf_counter() - t0

    depth = (plan.act_out[0] or plan.grad_out[0] or plan.report).depth
    limit = max(1, min(M, depth))

    def run_flush_v1(vbase: int) -> None:
        """The PR-8 one-chunk-per-stage eager 1F1B schedule, verbatim —
        virtual_stages=1 must execute it byte-for-byte."""
        fwd_m, bwd_m = [0], [0]
        a_in, g_in = act_in[0], grad_in[0]
        a_out, g_out = act_out[0], grad_out[0]

        def forward():
            t_mb = flight.now()
            m = fwd_m[0]
            fwd_m[0] += 1
            v = vbase + 2 * m
            x = read_value(in_ch if rt.first else a_in, v)
            if rt.last:
                labels = read_value(label_ch, v)
                _, gx = rt.loss_backward(0, x, labels)
                write_value(g_out, gx, v)
            else:
                write_value(a_out, rt.forward(0, m, x), v)
            _m_microbatches.inc(labels=stage_label)
            flight.span_since(_F_FWD, t_mb)

        def backward():
            m = bwd_m[0]
            bwd_m[0] += 1
            if rt.last:
                return  # folded into forward (fwd/bwd adjacent)
            t_mb = flight.now()
            v = vbase + 2 * m
            gy = read_value(g_in, v)
            gx = rt.backward(0, m, gy)
            if not rt.first:
                write_value(g_out, gx, v)
            flight.span_since(_F_BWD, t_mb)

        # Eager 1F1B: backward-first whenever the grad is already
        # committed (it frees a stash slot and feeds upstream),
        # otherwise run forwards ahead up to the channel-depth
        # in-flight bound. Strict 1F1B's fwd/bwd lockstep costs a
        # full pipeline round-trip of blocking per steady pair; the
        # eager order is the same math (backwards still run in
        # microbatch order, so accumulation is deterministic) under
        # the same memory bound — it just never parks while useful
        # work is ready. When nothing is ready, block on the edge
        # that must deliver next.
        fwd_src = in_ch if rt.first else a_in
        while bwd_m[0] < M:
            progressed = False
            if fwd_m[0] < M and fwd_m[0] - bwd_m[0] < limit \
                    and fwd_src.ready(vbase + 2 * fwd_m[0]):
                forward()
                progressed = True
            if bwd_m[0] < fwd_m[0] and (
                    rt.last or g_in.ready(vbase + 2 * bwd_m[0])):
                backward()
                progressed = True
            if progressed:
                continue
            # nothing committed yet: park on the required edge
            if bwd_m[0] < fwd_m[0] and (
                    fwd_m[0] == M or fwd_m[0] - bwd_m[0] >= limit):
                backward()
            else:
                forward()

    def run_flush_interleaved(vbase: int) -> None:
        """The interleaved multi-chunk schedule (virtual_stages > 1):
        eager over this stage's V chunks — deepest ready backward first
        (it feeds upstream soonest), else SHALLOWEST ready forward
        (earliest chunks feed everything downstream, so filling them
        first keeps every stage's deeper chunks supplied; measured
        better than deepest-first on the bubble probe), else an idle
        poll that IS the measured bubble. An op is "ready" only when
        its input is committed AND its local output slot is writable,
        so the actor never parks in one chunk's blocked write while
        another chunk has work (mirror edges can't be probed without an
        RPC and stay blocking, like the PR-8 chain)."""
        chs = rt.chunks
        fwd_m = [0] * V
        bwd_m = [0] * V

        def fwd_src(v):
            return in_ch if chs[v].first else act_in[v]

        def do_forward(v: int) -> None:
            t_mb = flight.now()
            m = fwd_m[v]
            fwd_m[v] += 1
            ver = vbase + 2 * m
            x = read_value(fwd_src(v), ver)
            if chs[v].last:
                labels = read_value(label_ch, ver)
                _, gx = rt.loss_backward(v, x, labels)
                write_value(grad_out[v], gx, ver)
                bwd_m[v] += 1  # fwd/bwd fused on the loss chunk
            else:
                write_value(act_out[v], rt.forward(v, m, x), ver)
            _m_microbatches.inc(labels=stage_label)
            flight.span_since(_F_FWD, t_mb)

        def do_backward(v: int) -> None:
            t_mb = flight.now()
            m = bwd_m[v]
            bwd_m[v] += 1
            ver = vbase + 2 * m
            gy = read_value(grad_in[v], ver)
            gx = rt.backward(v, m, gy)
            if not chs[v].first:
                write_value(grad_out[v], gx, ver)
            flight.span_since(_F_BWD, t_mb)

        def bwd_ready(v: int) -> bool:
            if chs[v].last or bwd_m[v] >= fwd_m[v]:
                return False
            ver = vbase + 2 * bwd_m[v]
            if not grad_in[v].ready(ver):
                return False
            w = grad_out[v]
            return w is None or w.writable(ver)

        def fwd_ready(v: int) -> bool:
            if fwd_m[v] >= M or fwd_m[v] - bwd_m[v] >= limit:
                return False
            ver = vbase + 2 * fwd_m[v]
            if not fwd_src(v).ready(ver):
                return False
            if chs[v].last:
                if not label_ch.ready(ver):
                    return False
                w = grad_out[v]
            else:
                w = act_out[v]
            return w is None or w.writable(ver)

        total = M * V
        idle = [0, 5e-5]  # spins, escalating delay (the _wait shape)
        while sum(bwd_m) < total:
            progressed = False
            for v in reversed(range(V)):
                if bwd_ready(v):
                    do_backward(v)
                    progressed = True
                    break
            if not progressed:
                for v in range(V):
                    if fwd_ready(v):
                        do_forward(v)
                        progressed = True
                        break
            if progressed:
                idle[0], idle[1] = 0, 5e-5
                continue
            # nothing ready on any chunk's edges: the pipeline bubble.
            # Poll with the channel-wait backoff — a close flips the
            # probes true (ready()/writable() return True on closed),
            # so the next pick raises instead of spinning forever.
            t0 = time.perf_counter()
            if idle[0] < 100:
                time.sleep(0)
            else:
                time.sleep(idle[1])
                idle[1] = min(idle[1] * 1.5, 0.002)
            idle[0] += 1
            if not first_read[0]:
                wait_box[0] += time.perf_counter() - t0

    # tp > 1: the deterministic static order every tp peer of this
    # (stage, dp-rank) slot executes identically — computed once, pure
    # function of (S, V, M, depth, stage)
    tp_order = (_simulate_tp_schedule(S, V, M, depth, s)
                if rt.tp > 1 else None)

    def run_flush_tp(vbase: int) -> None:
        """The tp static schedule: execute this stage's simulated op
        order with blocking reads/writes. Tail chunks (Megatron swiglu
        last block) return (u, mlp_partial); when the IMMEDIATELY next
        op is the same chunk's next forward, the tail allreduce runs
        async on the ".tail" group and overlaps that forward's compute —
        any other successor may transitively depend on the held act
        write, so the combine happens inline instead. At most one tail
        reduce is ever pending, and it is flushed before any other
        channel write (writes stay in version order)."""
        chs = rt.chunks
        pending = [None]  # (v, version, u, work)

        def flush_pending() -> None:
            v, ver, u, work = pending[0]
            pending[0] = None
            t0 = flight.now()
            y = rt.tail_combine(u, work)
            flight.span_since(_F_TPTAIL, t0)
            write_value(act_out[v], y, ver)

        n_ops = len(tp_order)
        for i, (kind, v, m) in enumerate(tp_order):
            ver = vbase + 2 * m
            if kind == "fwd":
                t_mb = flight.now()
                x = read_value(in_ch if chs[v].first else act_in[v], ver)
                if chs[v].last:
                    if pending[0] is not None:
                        flush_pending()
                    labels = read_value(label_ch, ver)
                    _, gx = rt.loss_backward(v, x, labels)
                    write_value(grad_out[v], gx, ver)
                elif chs[v].tail:
                    out = rt.forward(v, m, x)  # overlaps pending reduce
                    if pending[0] is not None:
                        flush_pending()  # version order on act_out[v]
                    u, mp = out
                    work = rt.tail_reduce_async(mp)
                    nxt = tp_order[i + 1] if i + 1 < n_ops else None
                    if rt.tp_overlap and nxt == ("fwd", v, m + 1):
                        pending[0] = (v, ver, u, work)
                    else:
                        t0 = flight.now()
                        y = rt.tail_combine(u, work)
                        flight.span_since(_F_TPTAIL, t0)
                        write_value(act_out[v], y, ver)
                else:
                    y = rt.forward(v, m, x)
                    if pending[0] is not None:
                        flush_pending()
                    write_value(act_out[v], y, ver)
                _m_microbatches.inc(labels=stage_label)
                flight.span_since(_F_FWD, t_mb)
            else:
                if pending[0] is not None:
                    flush_pending()
                t_mb = flight.now()
                gy = read_value(grad_in[v], ver)
                gx = rt.backward(v, m, gy)
                if not chs[v].first:
                    write_value(grad_out[v], gx, ver)
                flight.span_since(_F_BWD, t_mb)
        if pending[0] is not None:
            flush_pending()

    flush_idx = 0
    microbatches = 0
    try:
        while True:
            chaos.maybe_crash("worker.pipeline_step")
            t_fl = flight.now()
            t_box[0] = time.perf_counter()
            cpu0 = time.process_time()
            wait_box[0] = 0.0
            first_read[0] = True
            rpc_before = rpc._m_client_calls.total()
            tp_before = rt._tp_reduce_calls
            vbase = 2 * (flush_idx * M + 1)

            if rt.tp > 1:
                run_flush_tp(vbase)
            elif V == 1:
                run_flush_v1(vbase)
            else:
                run_flush_interleaved(vbase)

            microbatches += M * V
            t_opt = flight.now()
            flush_stats = rt.flush()
            flight.span_since(_F_OPT, t_opt)
            total_s = time.perf_counter() - t_box[0]
            bubble = min(1.0, wait_box[0] / max(total_s, 1e-9))
            # per-flush bubble as a counter track (basis points) — the
            # driver-side merge renders it alongside the wait spans it
            # is derived from
            flight.counter(_F_BUBBLE, int(bubble * 10_000))
            _m_flushes.inc(labels=stage_label)
            _m_stage_seconds.observe(total_s, labels=stage_label)
            _m_bubble.set(bubble, labels=stage_label)
            report = {
                "stage": s,
                "flush": flush_idx,
                "loss_sum": flush_stats["loss_sum"],
                "microbatches": M,
                "virtual_stages": V,
                "fused_bucket_applies":
                    flush_stats["fused_bucket_applies"],
                "tp": rt.tp,
                "tp_rank": rt.tp_rank,
                "tp_reduce_calls": rt._tp_reduce_calls - tp_before,
                "rpc_calls": rpc._m_client_calls.total() - rpc_before,
                "wait_s": wait_box[0],
                "flush_s": total_s,
                "cpu_s": time.process_time() - cpu0,
                "bubble_fraction": bubble,
                # this rank's registry values ride along so tests (and
                # the driver) can assert the wiring without an RPC to
                # the worker's /metrics endpoint
                "metrics": {
                    "microbatches_total": _m_microbatches.value(
                        labels=stage_label),
                    "flushes_total": _m_flushes.value(labels=stage_label),
                    "stage_seconds_count":
                        _m_stage_seconds.count_total(),
                    "fused_bucket_applies_total": _m_fused_applies.value(
                        labels=stage_label),
                },
            }
            report_w.write(serialization.pack(report), 2 * (flush_idx + 1))
            flight.span_since(_F_FLUSH, t_fl)
            flush_idx += 1
    except ChannelClosedError:
        # normal exit: trainer teardown (or a peer's death) closed the
        # channels; a half-done flush's gradient state dies with us.
        # Close OUR channels too before leaving: a peer that poisoned
        # only its own edges (user exception on a still-alive actor —
        # no supervisor death fan-out) relies on each stage propagating
        # the close, or the driver's untimed report read would hang.
        # Safe on the teardown path too: our pins (released in the
        # finally below, after this) keep the ranges alive, and the
        # driver frees them only after collecting this loop's result.
        try:
            close_everything()
        except Exception:
            logger.exception("pipeline close-on-exit failed")
        return {"flushes": flush_idx, "microbatches": microbatches}
    except BaseException:
        # stage math raised: poison the pipeline so every peer (and the
        # driver) unwinds instead of hanging, surface through this task
        try:
            close_everything()
        except Exception:
            logger.exception("pipeline close-on-error failed")
        raise
    finally:
        release_pins()


# ------------------------------------------------------------- stage actor


def _make_runtime(spec_blobs, stage, num_stages, virtual_stages,
                  num_microbatches, optimizer, dp, dp_rank, group_name,
                  fused_flush, flush_bucket_bytes,
                  declarative_group=False, tp=1, tp_rank=0,
                  tp_group=None, tp_tail_group=None,
                  tp_overlap=True) -> _StageRuntime:
    return _StageRuntime(
        [_as_stage_spec(b) for b in spec_blobs], stage, num_stages,
        virtual_stages, num_microbatches, optimizer, dp, dp_rank,
        group_name, fused_flush, flush_bucket_bytes,
        declarative_group=declarative_group, tp=tp, tp_rank=tp_rank,
        tp_group=tp_group, tp_tail_group=tp_tail_group,
        tp_overlap=tp_overlap)


class _PipelineStageActorImpl:
    """Stage actor body (wrapped by ray_tpu.remote at trainer build so
    importing this module never requires an initialized runtime)."""

    def __init__(self, spec_blobs, stage, num_stages, virtual_stages,
                 num_microbatches, optimizer, dp, dp_rank, group_name,
                 fused_flush, flush_bucket_bytes, declarative_group=False,
                 tp=1, tp_rank=0, tp_group=None, tp_tail_group=None,
                 tp_overlap=True):
        self._rt = _make_runtime(spec_blobs, stage, num_stages,
                                 virtual_stages, num_microbatches,
                                 optimizer, dp, dp_rank, group_name,
                                 fused_flush, flush_bucket_bytes,
                                 declarative_group, tp, tp_rank,
                                 tp_group, tp_tail_group, tp_overlap)

    def ping(self):
        return "ok"

    def run_loop(self, plan: _StagePlan) -> dict:
        return _run_stage_loop(self._rt, plan)

    # -- elastic rejoin (driver-orchestrated between run loops)

    def elastic_reset_group(self, dp: int, dp_rank: int) -> str:
        self._rt.reset_group(dp, dp_rank)
        return "ok"

    def elastic_sync_state(self, src_rank: int, timeout_ms: int) -> str:
        return self._rt.sync_state(src_rank, timeout_ms)

    # -- dynamic task-per-stage path (microbenchmark baseline; same math)

    def naive_fwd(self, v, m, x):
        return np.asarray(self._rt.forward(v, m, np.asarray(x)))

    def naive_loss_bwd(self, v, m, x, labels):
        _, gx = self._rt.loss_backward(v, np.asarray(x),
                                       np.asarray(labels))
        return np.asarray(gx)

    def naive_bwd(self, v, m, gy):
        gx = self._rt.backward(v, m, np.asarray(gy))
        return None if gx is None else np.asarray(gx)

    def naive_flush(self):
        return self._rt.flush()

    # -- introspection (valid before the loop starts or after it exits)

    def fetch_params(self, chunk: Optional[int] = None):
        import jax

        if chunk is not None:
            return jax.tree.map(np.asarray, self._rt.chunks[chunk].params)
        if self._rt.V == 1:
            return jax.tree.map(np.asarray, self._rt.chunks[0].params)
        return [jax.tree.map(np.asarray, ck.params)
                for ck in self._rt.chunks]


_stage_actor_cls = None


def _stage_actor():
    global _stage_actor_cls
    if _stage_actor_cls is None:
        import ray_tpu

        _stage_actor_cls = ray_tpu.remote(_PipelineStageActorImpl)
    return _stage_actor_cls


# ------------------------------------------------------------------ trainer


class PipelineTrainer:
    """Train a model sharded over S pipeline stages with (interleaved)
    1F1B microbatch scheduling over compiled-graph channels (module
    docstring has the design;
    `ray_tpu.models.presets.pipeline_stage_defs` partitions the
    transformer family into chunk specs).

        stages = presets.pipeline_stage_defs(cfg, num_stages=4,
                                             virtual_stages=2)
        trainer = PipelineTrainer(stages, num_microbatches=8,
                                  virtual_stages=2)
        for batch in data:                # {"tokens": [B, L] int32}
            out = trainer.step(batch)    # {"loss": ..., "reports": [...]}
        trainer.shutdown()

    ``stages`` holds ``S * virtual_stages`` chunk specs in pipeline
    order; chunk c runs on stage actor ``c % S`` (stage s owns chunks
    s, s+S, ... — the interleaved layout that shrinks the 1F1B bubble
    roughly by 1/V). ``dp`` replicates every stage; replicas sync
    gradients at flush with one coalesced-mean p2p allreduce per stage
    group — ``fused_flush`` (default) applies the optimizer per bucket
    as each reduce lands (leafwise optimizers only; pass False for
    cross-leaf optimizers, which is also the measured unfused
    baseline). ``mode="tasks"`` runs the same chunk math as dynamic
    actor tasks through the object store (the microbenchmark baseline).

    ``tensor_parallel=t`` (or ``RAY_TPU_PIPELINE_TP``) composes a THIRD
    axis: every (dp-rank, stage) slot becomes t actors, each holding the
    stage chunks' 1/t Megatron column/row shard (build the specs with
    ``pipeline_stage_defs(cfg, S, tensor_parallel=t)``). Activations
    and gradients still flow on per-rank act/grad slot rings; the
    partial-sum reduces ride per-(stage, dp-rank) tp collective groups
    (shm same-node / ring cross-node by the declarative rendezvous
    rule); the dp flush reduces only each rank's 1/t shard, so dp
    traffic drops by 1/t (weight-update sharding). Placement lands each
    tp group on ONE node (soft node-affinity pseudo-pod) while pipeline
    edges cross nodes. tp ranks execute a deterministic STATIC 1F1B
    schedule — the in-jit reduce callbacks pair by execution order, so
    the timing-dependent eager loops are structurally excluded.
    """

    def __init__(self, stages: Sequence[Any], *, num_microbatches: int,
                 dp: int = 1, mode: str = "channels",
                 optimizer: Any = ("sgd", 0.1),
                 virtual_stages: Optional[int] = None,
                 tensor_parallel: Optional[int] = None,
                 tp_overlap: bool = True,
                 fused_flush: bool = True,
                 flush_bucket_bytes: Optional[int] = None,
                 channel_depth: Optional[int] = None,
                 buffer_bytes: Optional[int] = None,
                 stage_options: Optional[Sequence[dict]] = None,
                 elastic: bool = False,
                 name: str = "pipeline"):
        from ray_tpu._private import api

        if mode not in ("channels", "tasks"):
            raise ValueError(f"unknown mode {mode!r}")
        if elastic and (mode != "channels" or int(dp) < 2):
            raise ValueError(
                "elastic=True needs mode='channels' and dp >= 2: a lost "
                "replica's parameters are recovered from a surviving dp "
                "peer over collective.broadcast, so there must be one")
        self._specs = [_as_stage_spec(s) for s in stages]
        core = api._require_core()
        self._core = core
        # tensor parallel width: None takes the env knob; an explicit 0
        # (argument or RAY_TPU_PIPELINE_TP=0) RAISES instead of silently
        # meaning 1 (the falsy-zero lesson)
        if tensor_parallel is None:
            t = int(core.config.pipeline_tp)
            t_source = "RAY_TPU_PIPELINE_TP"
        else:
            t = int(tensor_parallel)
            t_source = "tensor_parallel"
        if t < 1:
            raise ValueError(
                f"{t_source}={t} is invalid: tensor_parallel must be "
                f">= 1 (1 = no tensor parallelism; 0 does not mean "
                f"'default')")
        self._tp = t
        self._tp_overlap = bool(tp_overlap)
        spec_tps = {sp.tp for sp in self._specs}
        if spec_tps != {self._tp}:
            raise ValueError(
                f"tensor_parallel={self._tp} but the stage specs carry "
                f"tp={sorted(spec_tps)} — build them with "
                f"pipeline_stage_defs(cfg, S, tensor_parallel="
                f"{self._tp}) so the shard layout matches the trainer "
                f"grid")
        if self._tp > 1 and mode != "channels":
            raise ValueError(
                "tensor_parallel > 1 needs mode='channels': the tasks "
                "path runs one actor per (dp, stage) slot and cannot "
                "pair the in-jit tp reduces")
        if self._tp > 1 and elastic:
            raise ValueError(
                "tensor_parallel > 1 does not compose with elastic=True "
                "yet: a lost tp rank's shard has no replica inside its "
                "tp group to recover from")
        # interleaved virtual stages: None takes the env knob; an
        # explicit 0 (argument or RAY_TPU_PIPELINE_VIRTUAL_STAGES=0)
        # RAISES instead of silently meaning 1 (the falsy-zero lesson)
        if virtual_stages is None:
            v = int(core.config.pipeline_virtual_stages)
            v_source = "RAY_TPU_PIPELINE_VIRTUAL_STAGES"
        else:
            v = int(virtual_stages)
            v_source = "virtual_stages"
        if v < 1:
            raise ValueError(
                f"{v_source}={v} is invalid: virtual_stages must be >= 1 "
                f"(1 = one chunk per stage; 0 does not mean 'default')")
        self._V = v
        n = len(self._specs)
        if n % self._V != 0:
            raise ValueError(
                f"{n} chunk specs do not divide into virtual_stages="
                f"{self._V} chunks per stage — build them with "
                f"pipeline_stage_defs(cfg, S, virtual_stages={self._V}) "
                f"so len(stages) == S * {self._V}")
        self._S = n // self._V
        if self._S < 2:
            raise ValueError(
                "PipelineTrainer needs >= 2 stages (single-stage training "
                "has no pipeline; use JaxTrainer / models.training)")
        self._M = int(num_microbatches)
        if self._M < 1:
            raise ValueError("num_microbatches must be >= 1")
        if flush_bucket_bytes is not None and int(flush_bucket_bytes) < 1:
            raise ValueError(
                f"flush_bucket_bytes={flush_bucket_bytes} is invalid: "
                f"pass None for the RAY_TPU_COLLECTIVE_COALESCE_BYTES "
                f"default (0 does not mean 'default')")
        self._dp = int(dp)
        self._mode = mode
        self._name = name
        self._fused = bool(fused_flush)
        self._buffer = int(buffer_bytes or core.config.channel_buffer_bytes)
        cfg_depth = int(core.config.channel_depth or 1)
        # 1F1B wants room for the in-flight microbatch differential
        # (S*V chunks deep when interleaved); the config knob only wins
        # when the operator raised it higher
        self._depth = int(channel_depth) if channel_depth is not None \
            else max(2, min(self._S * self._V + 1, self._M), cfg_depth)
        if self._depth < 1:
            raise ValueError("channel_depth must be >= 1")
        self._flush = 0
        # channel-version flush counter: tracks self._flush except that
        # an elastic heal RESETS it (fresh channels + restarted loops
        # start at version 0 again while the user-visible step count
        # keeps climbing)
        self._vflush = 0
        self._dead = False
        self._torn = False
        self._teardown_lock = threading.Lock()
        self._all_specs: List[_channels.ChannelSpec] = []
        self._local_channels: Dict[bytes, _channels.LocalChannel] = {}
        self._loop_refs: List[Any] = []
        self._actor_info: Dict[str, dict] = {}
        self._actor_subs: Dict[str, Any] = {}
        self._slot_of_hex: Dict[str, Tuple[int, int, int]] = {}

        # ---- elastic membership (ISSUE 16)
        self._elastic = bool(elastic)
        self._optimizer = optimizer
        self._flush_bucket_bytes = flush_bucket_bytes
        self._note_lock = threading.Lock()
        self._lost_hexes: set = set()
        self._heal_pending = False
        self._heal_t0 = 0.0
        self._groups: List[Any] = []
        self._sup = None
        if self._elastic:
            from ray_tpu._private.elastic import ElasticSupervisor

            self._sup = ElasticSupervisor(name=name)

        # ---- stage actors (dp x S)
        import uuid

        # fold a per-trainer token into the collective group names: two
        # concurrently-live trainers with the default name must not meet
        # in rendezvous (they would cross-average unrelated models)
        token = uuid.uuid4().hex[:8]
        self._token = token
        self._stage_opts = list(stage_options or [])

        # axis-aware placement (tp > 1): each (dp-rank, stage) slot's tp
        # group should land on ONE node — a pseudo-pod whose tp reduces
        # rendezvous over shared memory — while pipeline edges cross
        # nodes. Soft affinity: a full node falls back to the scheduler,
        # and _build_channels verifies the outcome (ring transport keeps
        # cross-node placement correct, just slower).
        self._placement_plan: Optional[List[List[str]]] = None
        if self._tp > 1 and mode == "channels":
            try:
                views = core._run(core.clients.get(
                    core.controller_addr).call("node_views"))
                self._placement_plan = _channels.plan_axis_placement(
                    views, num_stages=self._S, dp=self._dp)
            except Exception:
                logger.debug("axis placement planning failed; leaving "
                             "stage placement to the scheduler",
                             exc_info=True)

        # actor grid: dp x S x tp (tp axis is size 1 unless composed)
        self._actors: List[List[List[Any]]] = []
        for r in range(self._dp):
            row = []
            for s in range(self._S):
                row.append([self._spawn_stage_actor(r, s, t)
                            for t in range(self._tp)])
            self._actors.append(row)
        for r in range(self._dp):
            for s in range(self._S):
                for t in range(self._tp):
                    self._slot_of_hex[
                        self._actors[r][s][t]._actor_id.hex()] = (r, s, t)
        import ray_tpu

        ray_tpu.get([a.ping.remote()
                     for row in self._actors
                     for cell in row for a in cell], timeout=120)

        if self._tp > 1:
            # declare the per-(stage, dp-rank) tp groups (plus the
            # ".tail" twin for the async last-block partial sums):
            # members rendezvous lazily on their first reduce, so the
            # control RPCs land in flush 0 and steady flushes stay
            # RPC-free
            from ray_tpu.util import collective as col

            ranks = list(range(self._tp))
            for r in range(self._dp):
                for s in range(self._S):
                    gname = self._tp_group_name(r, s)
                    col.create_collective_group(
                        self._actors[r][s], world_size=self._tp,
                        ranks=ranks, backend="host", group_name=gname)
                    col.create_collective_group(
                        self._actors[r][s], world_size=self._tp,
                        ranks=ranks, backend="host",
                        group_name=gname + ".tail")

        if self._elastic:
            # driver-declared (resizable) dp group per stage: members
            # rendezvous lazily at the current generation; a heal
            # re-declares at the next one
            from ray_tpu.util.collective.resizable import ResizableGroup

            # elastic excludes tp > 1 (validated above), so the tp axis
            # is always the singleton rank 0 here
            self._groups = [
                ResizableGroup(
                    [self._actors[r][s][0] for r in range(self._dp)],
                    group_name=f"{name}.{token}.stage{s}", backend="host")
                for s in range(self._S)]

        if mode == "channels":
            try:
                self._build_channels()
            except BaseException:
                try:
                    self.shutdown()
                except Exception:
                    logger.debug("pipeline build unwind failed",
                                 exc_info=True)
                raise

    # -- properties the microbenchmark guard keys on

    @property
    def is_channel_backed(self) -> bool:
        return self._mode == "channels" and bool(self._all_specs)

    @property
    def channel_depth(self) -> int:
        return self._depth if self.is_channel_backed else 0

    @property
    def num_stages(self) -> int:
        return self._S

    @property
    def virtual_stages(self) -> int:
        return self._V

    @property
    def tensor_parallel(self) -> int:
        return self._tp

    # -- build

    def _dp_group_name(self, s: int, t: int) -> str:
        """Per-stage dp flush group. At tp > 1 each tp rank's dp group
        is DISJOINT — rank t's flush reduces only its own 1/tp shard
        (weight-update sharding: dp traffic drops by 1/tp). tp == 1
        keeps the historical name byte-for-byte."""
        base = f"{self._name}.{self._token}.stage{s}"
        return base if self._tp == 1 else f"{base}.tp{t}"

    def _tp_group_name(self, r: int, s: int) -> str:
        """Per-(stage, dp-rank) tp reduce group (".tail" twin rides the
        async last-block partial sums)."""
        return f"{self._name}.{self._token}.stage{s}.dp{r}.tp"

    def _spawn_stage_actor(self, r: int, s: int, t: int = 0):
        """Create the (r, s, t) stage actor — the build path and the
        elastic respawn path run the exact same spawn."""
        cls = _stage_actor()
        opts = self._stage_opts
        if s < len(opts) and opts[s]:
            acls = cls.options(**opts[s])
        elif self._placement_plan is not None:
            from ray_tpu.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy)

            acls = cls.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id_hex=self._placement_plan[r][s], soft=True))
        else:
            acls = cls
        chunk_specs = [self._specs[s + u * self._S]
                       for u in range(self._V)]
        tp_group = self._tp_group_name(r, s) if self._tp > 1 else None
        return acls.remote(
            chunk_specs, s, self._S, self._V, self._M, self._optimizer,
            self._dp, r, self._dp_group_name(s, t),
            self._fused, self._flush_bucket_bytes, self._elastic,
            self._tp, t, tp_group,
            tp_group + ".tail" if tp_group else None, self._tp_overlap)

    def _create_channel(self, node_addr, n_readers, participants, *,
                        depth: Optional[int] = None,
                        buffer: Optional[int] = None
                        ) -> _channels.ChannelSpec:
        core = self._core
        spec = _channels.create_channel(
            core, node_addr, buffer or self._buffer,
            depth or self._depth, n_readers, participants)
        self._all_specs.append(spec)
        if tuple(node_addr) == tuple(core.supervisor_addr):
            self._local_channels[spec.key()] = _channels.LocalChannel(
                core.arena, spec)
        return spec

    def _build_channels(self) -> None:
        core = self._core
        driver_node = tuple(core.supervisor_addr)
        if core.arena is None:
            raise RuntimeError(
                "pipeline channels need a driver attached to a node arena")

        # resolve every stage actor's placement (one cluster-view
        # snapshot for the whole dp x S x tp pass; actors don't migrate
        # between the per-actor ALIVE waits and channel creation) — and
        # verify the axis plan's soft affinity landed when one exists
        # (a miss only downgrades the tp reduces to the cross-node ring)
        views = core._run(core.clients.get(core.controller_addr).call(
            "node_views"))
        for row in self._actors:
            for cell in row:
                for a in cell:
                    hexid = a._actor_id.hex()
                    expect = None
                    if self._placement_plan is not None:
                        (r, s, _t) = self._slot_of_hex[hexid]
                        expect = self._placement_plan[r][s]
                    self._actor_info[hexid] = \
                        _channels.resolve_actor_placement(
                            core, a._actor_id, views,
                            expect_node_id_hex=expect)

        # ANY participant's death closes every channel of the trainer:
        # stages are serially dependent, dp replicas meet at the flush
        # allreduce, and tp ranks meet at every in-jit reduce, so no
        # subset can make progress alone
        participants = {core._store_client_id}
        for info in self._actor_info.values():
            participants.add(info["worker_id_hex"])
            participants.add(f"node:{info['node_id_hex']}")

        def node_of(r, s, t):
            return self._actor_info[
                self._actors[r][s][t]._actor_id.hex()]["node_addr"]

        S, V, TP = self._S, self._V, self._tp
        C = S * V  # total pipeline chunks
        self._in_specs, self._label_specs = [], []
        self._report_readers: List[List[_channels.LocalChannel]] = []
        plans: List[List[_StagePlan]] = []  # flat (r * TP + t) -> [s]
        for r in range(self._dp):
            for t in range(TP):
                # each tp rank runs its own full act/grad ring chain —
                # activations are replicated across tp peers (identical
                # math on 1/tp param shards), so rank t's chunk c feeds
                # rank t's chunk c+1 with no cross-rank channel hop
                in_spec = self._create_channel(
                    node_of(r, 0, t), 1, participants)
                label_spec = self._create_channel(
                    node_of(r, S - 1, t), 1, participants)
                # per-chunk edges between the SAME S actors: chunk c
                # runs on actor c % S, so edge c -> c+1 lands on actor
                # (c+1) % S's node (channels live on the READER's
                # node). V=1, tp=1 reduces to the PR-8 neighbor-chain
                # plan exactly
                act = [self._create_channel(
                    node_of(r, (c + 1) % S, t), 1, participants)
                    for c in range(C - 1)]
                grad = [self._create_channel(
                    node_of(r, c % S, t), 1, participants)
                    for c in range(C - 1)]
                # reports carry one small stats dict per flush, and the
                # driver acks flush f before scattering f+1 — depth 1
                # and a small buffer, not S+1 slots of activation-sized
                # pinned arena each
                reports = [self._create_channel(
                    driver_node, 1, participants, depth=1,
                    buffer=64 * 1024) for _ in range(S)]
                self._in_specs.append(in_spec)
                self._label_specs.append(label_spec)
                self._report_readers.append(
                    [self._local_channels[sp.key()] for sp in reports])

                def stage_plan(s: int, in_spec=in_spec,
                               label_spec=label_spec, act=act,
                               grad=grad, reports=reports) -> _StagePlan:
                    cs = [s + u * S for u in range(V)]
                    return _StagePlan(
                        in_spec=in_spec if s == 0 else None,
                        label_spec=label_spec if s == S - 1 else None,
                        act_in=[act[c - 1] if c > 0 else None
                                for c in cs],
                        act_out=[act[c] if c < C - 1 else None
                                 for c in cs],
                        grad_in=[grad[c] if c < C - 1 else None
                                 for c in cs],
                        grad_out=[grad[c - 1] if c > 0 else None
                                  for c in cs],
                        report=reports[s],
                    )

                plans.append([stage_plan(s) for s in range(S)])

        # driver-side input writers (local write or mirror push)
        def driver_writer(spec):
            if tuple(spec.node_addr) == driver_node:
                return ("local", self._local_channels[spec.key()])
            return ("mirror", _channels.MirrorWriter(core, spec))

        self._in_writers = [driver_writer(sp) for sp in self._in_specs]
        self._label_writers = [driver_writer(sp) for sp in self._label_specs]

        # participant death -> close everything so nobody hangs; the
        # per-actor closure keeps WHICH actor died (the fan-out message
        # carries only the state — the topic is the identity), which the
        # elastic heal needs to pick the respawn slots
        for hexid in self._actor_info:
            cb = self._make_actor_cb(hexid)
            self._actor_subs[hexid] = cb
            core.subscribe("actor:" + hexid, cb)

        # start the run loops (they dedicate the actors until teardown)
        for r in range(self._dp):
            for t in range(self._tp):
                for s in range(self._S):
                    self._loop_refs.append(
                        self._actors[r][s][t].run_loop.remote(
                            plans[r * self._tp + t][s]))

    # -- failure fan-out (same shape as dag._ChannelGraph)

    def _make_actor_cb(self, hexid: str):
        def cb(message) -> None:
            if self._torn or not isinstance(message, dict):
                return
            if message.get("state") in ("DEAD", "RESTARTING"):
                self._note_death(hexid)
        return cb

    def _note_death(self, hexid: str) -> None:
        if not self._elastic:
            if self._dead:
                return
            self._close_for_failure()
            return
        # elastic: remember the slot, mark a heal pending (the next
        # step() boundary runs it), and close the channels so every loop
        # unwinds to that boundary — the PR-4 poison invariant: nobody
        # resumes a torn round, survivors rejoin the next generation
        with self._note_lock:
            if not self._heal_pending:
                self._heal_pending = True
                self._heal_t0 = time.monotonic()
            self._lost_hexes.add(hexid)
        slot = self._slot_of_hex.get(hexid)
        if slot is not None and self._groups:
            try:
                self._groups[slot[1]].note_departure(hexid)
            except Exception:
                logger.debug("note_departure failed", exc_info=True)
        self._close_for_failure()

    def _close_for_failure(self) -> None:
        """Close the whole pipeline (same lightweight fan-out as actor
        death): used when a step failed partway through its microbatch
        scatter — some channels carry the version, others never will, so
        a retried step would train on a MIX of two batches."""
        self._dead = True
        _channels.close_channels_nowait(
            self._core, self._local_channels.values(), self._all_specs)

    def _surface_failure(self, closed: ChannelClosedError):
        # a ChannelClosedError may wrap a TRANSPORT failure (a mirror
        # push that timed out against a still-healthy remote) — close
        # everything first so no stage loop stays parked on a version
        # that will never be written (CompiledDAG.execute's rule)
        self._close_for_failure()
        _channels.surface_loop_failure(self._core, self._loop_refs, closed)

    # -- elastic heal (runs at the step() boundary, never mid-flush)

    def _heal(self) -> None:
        """Re-form the world after noted departures: respawn the dead
        slots (budget/backoff via ElasticSupervisor), resize the
        affected stage dp groups to a fresh generation, broadcast
        params/opt state from a surviving replica to each replacement,
        rebuild the channel plan and restart the loops."""
        while True:
            with self._note_lock:
                if not self._heal_pending:
                    return
                self._heal_pending = False
                lost, self._lost_hexes = self._lost_hexes, set()
            self._heal_once(lost)

    def _heal_once(self, lost: set) -> None:
        import ray_tpu

        core = self._core
        t0 = self._heal_t0
        dead_slots = sorted(self._slot_of_hex[h] for h in lost
                            if h in self._slot_of_hex)
        logger.info("pipeline %s: healing after loss of %s",
                    self._name, dead_slots or sorted(lost))

        # 1. drain the old world: loops exited on the channel close;
        # collect them, drop the old subscriptions, free the old specs
        for ch in self._local_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        for ref in self._loop_refs:
            try:
                core.get([ref], timeout=self._sup.resize_timeout_s)
            except Exception:
                pass
        for hexid, cb in self._actor_subs.items():
            try:
                core.unsubscribe("actor:" + hexid, cb)
            except Exception:
                pass
        self._actor_subs.clear()
        try:
            _channels.free_and_unpin_specs(core, self._all_specs)
        except Exception:
            logger.debug("elastic spec free failed", exc_info=True)
        self._all_specs = []
        self._local_channels = {}
        self._loop_refs = []
        self._actor_info = {}

        # 2. respawn the dead slots (budget + backoff per slot) —
        # elastic excludes tp > 1, so the tp axis is always rank 0
        for (r, s, _t) in dead_slots:
            old_hex = self._actors[r][s][0]._actor_id.hex()
            self._slot_of_hex.pop(old_hex, None)
            a = self._sup.respawn(
                ("dp", r, "stage", s),
                lambda r=r, s=s: self._spawn_stage_actor(r, s))
            self._actors[r][s][0] = a
            self._slot_of_hex[a._actor_id.hex()] = (r, s, 0)
        if dead_slots:
            ray_tpu.get([self._actors[r][s][0].ping.remote()
                         for (r, s, _t) in dead_slots], timeout=120)

        # 3. reshard: re-declare each affected stage's dp group at the
        # next generation, then deliver params/opt state to the joiner
        # from the lowest-rank survivor (leaf-wise broadcast — no
        # checkpoint restore anywhere on this path)
        t_ms = self._sup.resize_timeout_ms
        for s in sorted({s for (_, s, _t) in dead_slots}):
            dead_rs = {r for (r, ss, _t) in dead_slots if ss == s}
            live = [r for r in range(self._dp) if r not in dead_rs]
            if not live:
                raise RuntimeError(
                    f"pipeline {self._name}: every dp replica of stage "
                    f"{s} died — parameters are unrecoverable without a "
                    f"checkpoint; treating the outage as terminal")
            row = [self._actors[r][s][0] for r in range(self._dp)]
            self._groups[s].resize(row)
            ray_tpu.get([row[r].elastic_reset_group.remote(self._dp, r)
                         for r in range(self._dp)], timeout=120)
            refs = [row[r].elastic_sync_state.remote(live[0], t_ms)
                    for r in range(self._dp)]
            ray_tpu.get(refs, timeout=t_ms / 1000.0 + 30)

        # 4. restart the world: fresh channels + loops (versions restart
        # at 0 — _vflush resets with them; the user-visible step count
        # does not)
        self._vflush = 0
        try:
            self._build_channels()
        except BaseException:
            self._close_for_failure()
            raise
        with self._note_lock:
            if not self._heal_pending:
                self._dead = False
        self._sup.rejoin_span(t0)
        logger.info("pipeline %s: healed (%d respawn(s), epoch(s) %s)",
                    self._name, len(dead_slots),
                    [g.epoch for g in self._groups])

    # -- stepping

    def _split(self, batch) -> List[List[np.ndarray]]:
        if isinstance(batch, dict):
            extra = set(batch) - {"tokens"}
            if extra:
                # dropping keys silently (e.g. a loss_fn-style 'mask')
                # would train on different math than the user believes
                raise ValueError(
                    f"PipelineTrainer batches support only {{'tokens'}}; "
                    f"got extra keys {sorted(extra)} (masking is not "
                    f"threaded through the stage loss yet)")
            tokens = batch["tokens"]
        else:
            tokens = batch
        tokens = np.asarray(tokens)
        B = tokens.shape[0]
        per = self._dp * self._M
        if B % per != 0:
            raise ValueError(
                f"batch size {B} not divisible by dp*num_microbatches "
                f"({self._dp}x{self._M})")
        mb = B // per
        return [[tokens[(r * self._M + m) * mb:(r * self._M + m + 1) * mb]
                 for m in range(self._M)] for r in range(self._dp)]

    def step(self, batch) -> Dict[str, Any]:
        """One optimizer step: scatter M microbatches per dp replica into
        the pipeline, collect every stage's flush report, return the mean
        loss. Steady-state cost: channel writes/reads only."""
        if self._mode == "tasks":
            return self._step_tasks(batch)
        if self._elastic and self._heal_pending and not self._torn:
            self._heal()
        if self._dead:
            raise ChannelClosedError("pipeline trainer was torn down")
        mbs = self._split(batch)
        vbase = 2 * (self._vflush * self._M + 1)
        wrote = False
        try:
            for r in range(self._dp):
                for m, mb in enumerate(mbs[r]):
                    payload = serialization.pack(np.ascontiguousarray(mb))
                    v = vbase + 2 * m
                    # every tp rank of the replica gets the SAME
                    # microbatch: activations are replicated across the
                    # tp axis, only params are sharded
                    for t in range(self._tp):
                        idx = r * self._tp + t
                        for kind, w in (self._in_writers[idx],
                                        self._label_writers[idx]):
                            if kind == "local":
                                w.write(payload, v)
                            else:
                                w.push(payload, v)
                            wrote = True
        except ChannelClosedError as e:
            self._surface_failure(e)
        except BaseException:
            if wrote:
                # a partial scatter is unrecoverable: stage 0 already
                # acked some of this flush's microbatches, so a retried
                # step() would silently mix two batches into one
                # gradient — close the pipeline instead (same rule as
                # CompiledDAG.execute)
                self._close_for_failure()
            raise
        rv = 2 * (self._vflush + 1)
        reports: List[dict] = []
        try:
            for idx, readers in enumerate(self._report_readers):
                for ch in readers:
                    view = ch.read(rv)
                    rep = serialization.unpack(bytes(view))
                    del view
                    ch.ack(0, rv)
                    rep["dp_rank"] = idx // self._tp
                    reports.append(rep)
        except ChannelClosedError as e:
            self._surface_failure(e)
        self._flush += 1
        self._vflush += 1
        last = [rep for rep in reports if rep["stage"] == self._S - 1]
        loss = float(np.mean([rep["loss_sum"] / rep["microbatches"]
                              for rep in last]))
        return {"loss": loss, "step": self._flush, "reports": reports}

    # -- dynamic task-per-stage baseline (object-store data plane)

    def _step_tasks(self, batch) -> Dict[str, Any]:
        import ray_tpu

        mbs = self._split(batch)
        S, V = self._S, self._V
        C = S * V
        barriers = []
        for r in range(self._dp):
            # tasks mode excludes tp > 1 (validated in __init__): the
            # tp axis is the singleton rank 0
            row = [cell[0] for cell in self._actors[r]]
            for m, mb in enumerate(mbs[r]):
                # chunk c runs on actor c % S as local chunk c // S —
                # the same interleaved layout the channel loops execute
                ref = row[0].naive_fwd.remote(0, m, mb)
                for c in range(1, C - 1):
                    ref = row[c % S].naive_fwd.remote(c // S, m, ref)
                gref = row[(C - 1) % S].naive_loss_bwd.remote(
                    (C - 1) // S, m, ref, mb)
                for c in range(C - 2, -1, -1):
                    gref = row[c % S].naive_bwd.remote(c // S, m, gref)
                barriers.append(gref)
        ray_tpu.get(barriers, timeout=600)
        stats = ray_tpu.get(
            [cell[0].naive_flush.remote()
             for row in self._actors for cell in row],
            timeout=600)
        self._flush += 1
        last = stats[self._S - 1::self._S]
        loss = float(np.mean([st["loss_sum"] / st["microbatches"]
                              for st in last]))
        return {"loss": loss, "step": self._flush, "reports": stats}

    # -- introspection / teardown

    def fetch_params(self, stage: int, dp_rank: int = 0,
                     chunk: Optional[int] = None, tp_rank: int = 0):
        """Stage shard params (tasks mode anytime; channels mode after
        shutdown — the run loop dedicates the actor). At
        virtual_stages=1 returns the stage's single chunk tree; at V > 1
        a list of the stage's V chunk trees (or one tree with
        ``chunk=`` the local index). At tensor_parallel > 1 the result
        is ``tp_rank``'s 1/tp shard — reassemble the fused tree with
        ``presets.reassemble_pipeline_params``."""
        import ray_tpu

        return ray_tpu.get(
            self._actors[dp_rank][stage][tp_rank]
                .fetch_params.remote(chunk),
            timeout=120)

    def shutdown(self, kill_actors: bool = True,
                 timeout: float = 30) -> Dict[str, Any]:
        """Close every channel, stop the stage loops, release the pins,
        (optionally) kill the stage actors. Idempotent."""
        self._dead = True
        # only the FIRST call may run the release: after it frees the
        # channel ranges they can be recycled to a NEWER trainer/graph,
        # and a repeat close (e.g. __del__ racing an explicit shutdown
        # from another thread) would stamp the closed flag into live
        # channels that aren't ours anymore (the dag teardown rule)
        with self._teardown_lock:
            if self._torn:
                return {}
            self._torn = True
        core = self._core
        for ch in self._local_channels.values():
            try:
                ch.close()
            except Exception:
                pass
        for hexid, cb in self._actor_subs.items():
            try:
                core.unsubscribe("actor:" + hexid, cb)
            except Exception:
                pass
        self._actor_subs = {}

        _channels.close_specs(core, self._all_specs)
        stats: Dict[str, Any] = {"loops": []}
        for ref in self._loop_refs:
            try:
                stats["loops"].append(core.get([ref], timeout=timeout)[0])
            except Exception:
                stats["loops"].append(None)
        _channels.free_and_unpin_specs(core, self._all_specs)
        if kill_actors:
            import ray_tpu

            for row in self._actors:
                for cell in row:
                    for a in cell:
                        try:
                            ray_tpu.kill(a)
                        except Exception:
                            pass
        return stats

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
