"""Experiment/trial storage layout.

Analog of `ray.train._internal.storage.StorageContext`
(`python/ray/train/_internal/storage.py`): owns the
``storage_path/experiment_name/trial_dir`` layout and persists worker
checkpoints into it. Filesystem only for now (a TPU pod's hosts mount GCS
via gcsfuse or share NFS); the persist step is a tree merge so multi-host
orbax shards from different ranks land in one checkpoint directory.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ray_tpu.train._checkpoint import Checkpoint, _merge_tree


class StorageContext:
    def __init__(
        self,
        storage_path: str,
        experiment_dir_name: str,
        trial_dir_name: Optional[str] = None,
    ):
        self.storage_path = os.path.abspath(os.path.expanduser(storage_path))
        self.experiment_dir_name = experiment_dir_name
        self.trial_dir_name = trial_dir_name
        self.current_checkpoint_index = 0

    @property
    def experiment_fs_path(self) -> str:
        return os.path.join(self.storage_path, self.experiment_dir_name)

    @property
    def trial_fs_path(self) -> str:
        if self.trial_dir_name is None:
            return self.experiment_fs_path
        return os.path.join(self.experiment_fs_path, self.trial_dir_name)

    def make_dirs(self) -> None:
        os.makedirs(self.trial_fs_path, exist_ok=True)

    def checkpoint_fs_path(self, index: Optional[int] = None) -> str:
        idx = self.current_checkpoint_index if index is None else index
        return os.path.join(self.trial_fs_path, f"checkpoint_{idx:06d}")

    def persist_current_checkpoint(self, checkpoint: Checkpoint) -> Checkpoint:
        """Merge-copy a worker-local checkpoint dir into trial storage."""
        dest = self.checkpoint_fs_path()
        os.makedirs(dest, exist_ok=True)
        _merge_tree(checkpoint.path, dest)
        return Checkpoint(dest)

    def advance_checkpoint_index(self) -> None:
        self.current_checkpoint_index += 1

    def __getstate__(self):
        return dict(self.__dict__)

    def __setstate__(self, state):
        self.__dict__.update(state)


def make_experiment_name(prefix: str = "train") -> str:
    return f"{prefix}_{time.strftime('%Y-%m-%d_%H-%M-%S')}_{os.getpid()}"
