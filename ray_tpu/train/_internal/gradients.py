"""Data-parallel gradient averaging for `ray_tpu.train` worker loops.

The train analog of the RLlib learner's `_allreduce_grads`: a worker
group's ranks average their gradient trees over the host-backend
collective data plane (shm on one node, ring across nodes), riding the
async overlap API so the host-side movement hides behind device compute:

    from ray_tpu.train import GradientAverager

    def train_loop_per_worker():
        avg = GradientAverager()          # ranks/world from the session
        for batch in loader:
            grads = grad_fn(params, batch)         # device arrays
            work = avg.begin(grads)                # returns immediately
            aux = other_device_work()              # overlaps the reduce
            grads = work.wait_tree()               # averaged tree
            params = apply(params, grads)

`average(grads)` is the one-call form (begin + wait). Buckets
materialize device->host one batched transfer at a time in
reverse-backward order, a MEAN is pre-scaled into the pack copy, and the
averager keeps persistent landing buffers, so a steady-state step
allocates nothing. ``RAY_TPU_COLLECTIVE_OVERLAP=0`` drops the whole
path to the synchronous coalesced reduce without any call-site change.
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class _TreeWork:
    """Wraps a CollectiveWork so callers get the tree back, not leaves."""

    def __init__(self, work, treedef, as_device: bool):
        self._work = work
        self._treedef = treedef
        self._as_device = as_device

    def done(self) -> bool:
        return self._work.done()

    def wait_tree(self, timeout_ms: Optional[int] = None):
        import jax

        leaves = self._work.wait(timeout_ms)
        if self._as_device:
            import jax.numpy as jnp

            # copy=True: the averager's landing buffers are reused next
            # step; an aliasing device_put would race the next reduce
            leaves = [jnp.array(x) for x in leaves]
        return jax.tree.unflatten(self._treedef, leaves)


class GradientAverager:
    """Per-worker handle on the training group's gradient collective.

    ``world_size``/``rank`` default to the train session's world rank
    (``ray_tpu.train.get_context()``), so a ``train_loop_per_worker``
    needs no arguments; pass them explicitly to use the averager outside
    a session (tests, custom actor pools). The group is initialized
    imperatively and idempotently on first use — every rank constructs
    its own averager, exactly like `jax.distributed` setup."""

    def __init__(self, group_name: str = "train_grads",
                 world_size: Optional[int] = None,
                 rank: Optional[int] = None,
                 timeout_ms: int = 60_000,
                 init_group: bool = True):
        """``init_group=False`` skips the imperative group init — for
        callers whose group membership is already published some other
        way (the RLlib learner rides its driver-declared "learners"
        group, whose generation machinery an imperative init would
        bypass)."""
        if world_size is None or rank is None:
            from ray_tpu.train._internal.session import get_session

            sess = get_session()
            if sess is None:
                raise RuntimeError(
                    "GradientAverager needs world_size/rank outside a "
                    "training worker session")
            world_size = sess.world_size if world_size is None else world_size
            rank = sess.world_rank if rank is None else rank
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.timeout_ms = timeout_ms
        self._out: Optional[List[np.ndarray]] = None
        self._sig: Optional[List[Any]] = None
        if world_size > 1 and init_group:
            from ray_tpu.util import collective

            if not collective.is_group_initialized(group_name):
                collective.init_collective_group(
                    world_size, rank, backend="host", group_name=group_name)

    def begin(self, grads: Any, on_bucket=None) -> _TreeWork:
        """Start the overlapped average of a gradient pytree; returns a
        handle whose ``wait_tree()`` yields the averaged tree. Device
        leaves are handed to the runner untouched — the device->host
        transfers are part of what overlaps. ``on_bucket(indices,
        arrays)`` (optional, flat-leaf indices in ``jax.tree.flatten``
        order) fires per coalesced bucket as its reduce lands, on the
        runner's reducer thread — the hook the fused in-bucket optimizer
        rides so a bucket's update overlaps the remaining buckets'
        rounds. NOTE: with on_bucket, the arrays alias this averager's
        persistent landing buffers — consume them inside the callback
        (e.g. dispatch the jitted apply), do not stash references past
        the next step."""
        import jax

        from ray_tpu.util import collective
        from ray_tpu.util.collective import ReduceOp
        from ray_tpu.util.collective.async_work import (_CompletedWork,
                                                        validate_on_bucket)

        validate_on_bucket(on_bucket)
        flat, tree = jax.tree.flatten(grads)
        if self.world_size <= 1:
            leaves = [np.asarray(f) for f in flat]
            if on_bucket is not None and leaves:
                # the solo fallback still honors the per-bucket contract
                # (fire_on_bucket IS the contract — same-dtype buckets,
                # runner order) so caller state machines keyed on bucket
                # completion see identical sequences at every world size
                from ray_tpu.util.collective.async_work import \
                    fire_on_bucket
                from ray_tpu.util.collective.collective import \
                    _default_bucket_bytes

                fire_on_bucket(leaves, _default_bucket_bytes(), leaves,
                               on_bucket)
            return _TreeWork(
                _CompletedWork(self.group_name, leaves),
                tree, as_device=True)
        # (shape, dtype) signature, not leaf count: a same-arity tree
        # with one resized leaf must reallocate the landing buffers
        sig = [(tuple(f.shape), np.dtype(f.dtype)) for f in flat]
        if self._out is None or self._sig != sig:
            self._out = [np.empty(s, d) for s, d in sig]
            self._sig = sig
        work = collective.allreduce_coalesced_async(
            flat, group_name=self.group_name, op=ReduceOp.MEAN,
            timeout_ms=self.timeout_ms, out=self._out,
            on_bucket=on_bucket)
        return _TreeWork(work, tree, as_device=True)

    def average(self, grads: Any) -> Any:
        """Synchronous convenience: ``begin(grads).wait_tree()``."""
        return self.begin(grads).wait_tree()

    def shutdown(self) -> None:
        """Destroy the group (fails any in-flight work cleanly)."""
        if self.world_size > 1:
            from ray_tpu.util import collective

            collective.destroy_collective_group(self.group_name)
