"""Trainers.

Analog of `ray.train.base_trainer.BaseTrainer` (`python/ray/train/
base_trainer.py:567` fit) and `ray.train.data_parallel_trainer.
DataParallelTrainer` (`python/ray/train/data_parallel_trainer.py:25`,
training_loop `:428`). The reference routes fit() through a single-trial
Tune run; here fit() drives the BackendExecutor directly, and the Tune
layer (`ray_tpu.tune`) wraps trainers as trainables instead — same
capability, inverted layering, which keeps the no-Tune path free of trial
overhead.

`JaxTrainer` is the TPU-native flagship (reference's TorchTrainer +
TorchXLAConfig path, `train/torch/xla/config.py:20`): workers form a
`jax.distributed` runtime; the user loop builds a Mesh over
`jax.devices()` and jits over it.
"""

from __future__ import annotations

import dataclasses
import inspect
import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.air.config import (CheckpointConfig, FailureConfig, RunConfig,
                                ScalingConfig)
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import (BackendExecutor,
                                                      TrainingFinished,
                                                      TrainingWorkerError)
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train._internal.storage import (StorageContext,
                                             make_experiment_name)
from ray_tpu.train.backend import BackendConfig, JaxConfig

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Result:
    """Analog of `ray.train.Result` (`python/ray/train/result.py`)."""

    metrics: Optional[Dict[str, Any]]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[Exception] = None
    config: Optional[Dict[str, Any]] = None  # set by tune trials
    metrics_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    best_checkpoints: List[Tuple[Checkpoint, Dict[str, Any]]] = (
        dataclasses.field(default_factory=list))


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError


class DataParallelTrainer(BaseTrainer):
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._backend_config = backend_config or BackendConfig()
        self.datasets = datasets or {}
        self._loop_takes_config = (
            len(inspect.signature(train_loop_per_worker).parameters) > 0)

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        name = self.run_config.name or make_experiment_name(
            type(self).__name__.lower())
        storage = StorageContext(self.run_config.storage_path, name)
        storage.make_dirs()
        ckpt_manager = CheckpointManager(self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures

        latest_checkpoint = self.resume_from_checkpoint
        checkpoint_index = 0
        metrics_history: List[Dict[str, Any]] = []
        last_metrics: Optional[Dict[str, Any]] = None
        error: Optional[Exception] = None
        failures = 0

        while True:
            executor = BackendExecutor(
                backend_config=self._backend_config,
                scaling_config=self.scaling_config,
                storage=storage,
                experiment_name=name,
                trial_name=name,
            )
            try:
                executor.start()
                executor.start_training(
                    self._wrapped_loop(),
                    (self._train_loop_config or {})
                    if self._loop_takes_config else None,
                    latest_checkpoint,
                    dataset_shards_per_worker=self._shard_datasets(),
                    checkpoint_index=checkpoint_index,
                )
                while True:
                    reports = executor.get_next_results()
                    checkpoint_index += 1
                    # rank 0's metrics are the run's metrics (reference
                    # semantics: session.py rank-0 reporting)
                    last_metrics = reports[0].metrics
                    metrics_history.append(last_metrics)
                    ckpt_paths = [
                        r.checkpoint_path for r in reports
                        if r.checkpoint_path
                    ]
                    if ckpt_paths:
                        ckpt = Checkpoint(ckpt_paths[0])
                        latest_checkpoint = ckpt
                        ckpt_manager.register_checkpoint(
                            ckpt, last_metrics or {}, checkpoint_index)
            except TrainingFinished:
                error = None
                break
            except TrainingWorkerError as e:
                failures += 1
                logger.warning("worker group failure %d: %s", failures, e)
                if max_failures >= 0 and failures > max_failures:
                    error = e
                    break
                latest_checkpoint = (ckpt_manager.latest_checkpoint
                                     or latest_checkpoint)
                logger.info(
                    "restarting worker group from %s",
                    latest_checkpoint.path if latest_checkpoint else "scratch")
            finally:
                executor.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=ckpt_manager.latest_checkpoint or latest_checkpoint,
            path=storage.trial_fs_path,
            error=error,
            metrics_history=metrics_history,
            best_checkpoints=ckpt_manager.best_checkpoints,
        )

    def _wrapped_loop(self):
        return self._train_loop

    def _shard_datasets(self) -> Optional[List[Dict[str, Any]]]:
        """Split each dataset into per-worker shards.

        Objects with `.streaming_split(n)` (ray_tpu.data.Dataset) are split
        once across workers; anything else is passed through whole.
        """
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        per_worker: List[Dict[str, Any]] = [{} for _ in range(n)]
        for dsname, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                # equal=True: lockstep SPMD loops need identical batch counts
                # per rank or the report barrier desynchronizes (reference
                # train ingest: data_config.py uses equal=True).
                shards = ds.streaming_split(n, equal=True)
            else:
                shards = [ds] * n
            for rank in range(n):
                per_worker[rank][dsname] = shards[rank]
        return per_worker


class JaxTrainer(DataParallelTrainer):
    """Data/FSDP/TP-parallel JAX training over TPU worker actors."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        jax_config: Optional[JaxConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        **kwargs,
    ):
        scaling_config = scaling_config or ScalingConfig()
        if jax_config is None:
            jax_config = JaxConfig(use_tpu=scaling_config.use_tpu)
        super().__init__(
            train_loop_per_worker,
            backend_config=jax_config,
            scaling_config=scaling_config,
            **kwargs,
        )
