"""ray_tpu.train — distributed training orchestration (Ray Train analog).

Public surface mirrors `ray.train` (`python/ray/train/__init__.py`):
Checkpoint, ScalingConfig/RunConfig/FailureConfig/CheckpointConfig,
report/get_checkpoint/get_context/get_dataset_shard, trainers.
"""

from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._checkpoint import Checkpoint  # noqa: F401
from ray_tpu.train._internal.session import (  # noqa: F401
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train._internal.gradients import GradientAverager  # noqa: F401
from ray_tpu.train._internal.pipeline import (  # noqa: F401
    PipelineTrainer,
    StageSpec,
)
from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig  # noqa: F401
from ray_tpu.train.trainer import (  # noqa: F401
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    Result,
)

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("train")
del _rlu
