"""Directory-backed checkpoints.

Analog of `ray.train.Checkpoint` (`python/ray/train/_checkpoint.py`): a
checkpoint IS a directory on a filesystem, nothing more. Orbax/flax
serialization composes on top — callers write an orbax checkpoint into a
directory and wrap it. Metadata rides in a sidecar JSON file.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator

_METADATA_FILE = ".ray_tpu_ckpt_metadata.json"


class Checkpoint:
    """A reference to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.path.expanduser(path))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        """Materialize into ``path`` (or a temp dir) and return it."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:12]}"
        )
        os.makedirs(dest, exist_ok=True)
        _merge_tree(self.path, dest)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Local-dir view. Local paths are yielded as-is (zero copy)."""
        yield self.path

    def get_metadata(self) -> Dict[str, Any]:
        meta = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(meta):
            with open(meta) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        merged = self.get_metadata()
        merged.update(metadata)
        self.set_metadata(merged)

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Checkpoint) and other.path == self.path

    def __hash__(self) -> int:
        return hash(self.path)


def _merge_tree(src: str, dest: str) -> None:
    """Recursive copy that merges into an existing tree (multi-rank
    checkpoint shards land in one directory)."""
    for root, dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        target = dest if rel == "." else os.path.join(dest, rel)
        os.makedirs(target, exist_ok=True)
        for f in files:
            shutil.copy2(os.path.join(root, f), os.path.join(target, f))
