"""Shared run/scaling/failure/checkpoint configs.

Analog of the reference's ``ray.air.config`` dataclasses
(`python/ray/air/config.py`: ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig), reshaped for TPU: ``use_tpu`` + an optional slice
``topology`` (e.g. ``"v5p-64"``) replace ``use_gpu``/``accelerator_type``,
and worker resources are expressed in chips.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many training workers and what each one holds.

    A worker is one *process* (one controller of a set of TPU chips). On a
    multi-host slice there is one worker per host, each seeing its local
    chips; ``num_workers`` therefore is the process count of the
    ``jax.distributed`` runtime the backend assembles.
    """

    num_workers: int = 1
    use_tpu: bool = False
    #: Chips each worker drives (0 = share whatever is visible).
    tpus_per_worker: Optional[float] = None
    topology: Optional[str] = None  # e.g. "v4-8", "v5p-64" — gang label
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    def __post_init__(self):
        if self.num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if self.tpus_per_worker is not None and self.tpus_per_worker < 0:
            raise ValueError("tpus_per_worker must be >= 0")

    @property
    def _worker_bundle(self) -> Dict[str, float]:
        bundle: Dict[str, float] = {"CPU": 1.0}
        if self.resources_per_worker:
            bundle.update(
                {k: float(v) for k, v in self.resources_per_worker.items()}
            )
        if self.use_tpu and "TPU" not in bundle:
            # explicit 0 means "share whatever is visible" — reserve nothing
            per = (float(self.tpus_per_worker)
                   if self.tpus_per_worker is not None else 1.0)
            if per > 0:
                bundle["TPU"] = per
        return bundle

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        return [dict(self._worker_bundle) for _ in range(self.num_workers)]

    @property
    def total_workers(self) -> int:
        return self.num_workers


@dataclasses.dataclass
class FailureConfig:
    """Retry budget for a whole run (`air/config.py` FailureConfig).

    ``max_failures``: 0 = no retries, n = retry up to n times, -1 = retry
    forever. A failure means the worker group died; recovery restarts the
    gang and resumes from the latest persisted checkpoint (mesh-level
    recovery per SURVEY §5 — a lost host invalidates the whole mesh, so
    per-object lineage does not apply to training state).
    """

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """Retention policy (`air/config.py` CheckpointConfig)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Experiment-level settings (`air/config.py` RunConfig)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    #: tune stop criteria: {"metric": threshold} — a trial stops once any
    #: reported metric reaches its threshold (reference RunConfig.stop)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
    callbacks: Optional[List[Any]] = None

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.path.join(
                os.path.expanduser("~"), "ray_tpu_results"
            )
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
