from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
