from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("air")
del _rlu
