"""Serve-decode throughput harness: batched autoregressive decode on the
local chip (the BASELINE "Serve-equivalent LLM deployment ... batched
replica throughput" row) plus the ISSUE-9 open-loop load generator.

Modes:

  * default — the jitted prefill + per-token decode loop from
    `ray_tpu.models.decode` across batch sizes (raw device decode
    capacity);
  * --serve — end-to-end through a live Serve deployment (router ->
    replica -> continuous scheduler);
  * --loadgen — OPEN-LOOP load generator against the replica serve path:
    Poisson arrivals; `--workload prefix` (default, ISSUE 13) draws each
    prompt as a Zipf-distributed shared preamble (8 x 224-token system
    prompts / few-shot preambles) plus a unique 4-10-token tail, while
    `--workload mixed` keeps the ISSUE-9 mixed-length/heavy-tail shape.
    Drives THREE schedulers at the same offered load — paged arena +
    radix prefix cache, the PR-9 contiguous continuous arena, and the
    request-level `@serve.batch` baseline — and reports p50/p99 TTFT,
    p50/p99 inter-token latency, useful tokens/s and `prefix_hit_rate`,
    plus the paged/continuous and continuous/baseline ratios. Records
    carry the PR-6 TPU-probe provenance fields (`tpu_lost`,
    `tpu_probe_ok`, `tpu_probe_attempts`, `device`) so CPU-smoke numbers
    are distinguishable from regressions.

    python bench_serve.py --loadgen [--rate 20] [--requests 60]
                          [--seed 0] [--json-out SERVE_BENCH.json]

vs_baseline of the default mode is decode tokens/s at the best batch
divided by 1000 (a single-GPU 7B-class continuous-batching serving rate
is O(1000) tok/s; the debug-size model here is smaller, so treat it as a
scale probe, not a model-for-model comparison).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time


def bench_decode(preset: str, prompt_len: int, new_tokens: int,
                 batches=(1, 8, 32)) -> dict:
    import functools

    import jax

    from ray_tpu.models import presets
    from ray_tpu.models.decode import generate
    from ray_tpu.models.transformer import init_params

    cfg = getattr(presets, preset)()
    params = init_params(cfg, jax.random.PRNGKey(0))
    # one compiled program per batch size: prefill + lax.scan over decode
    # steps — the replica-side program shape (per-token host dispatch
    # through the test tunnel would measure the tunnel, not the chip)
    gen = jax.jit(functools.partial(generate, cfg,
                                    max_new_tokens=new_tokens),
                  static_argnames=())

    results = []
    for batch in batches:
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        key = jax.random.PRNGKey(2)
        toks = gen(params, tokens, key)
        float(toks.sum())  # compile + warmup, host sync
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            toks = gen(params, tokens, key)
        float(toks.sum())
        dt = (time.perf_counter() - t0) / iters
        decode_tps = batch * new_tokens / dt
        results.append({
            "batch": batch,
            "decode_tokens_per_sec": round(decode_tps, 1),
            "latency_ms_per_token": round(dt / new_tokens * 1e3, 2),
            "end_to_end_s": round(dt, 3),
        })
    return {"per_batch": results, "preset": preset,
            "prompt_len": prompt_len, "new_tokens": new_tokens}


def bench_serve_path(preset: str, new_tokens: int, concurrency: int,
                     requests_total: int) -> dict:
    """End-to-end CONTINUOUS-BATCHING measurement: concurrent requests
    through a live Serve deployment (router -> replica -> @serve.batch
    coalescing -> one batched generate per flush), tokens/s counted at
    the client. This is the serving number; `bench_decode` is the raw
    device decode capacity it converges to as batching amortizes."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_app

    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    try:
        h = serve.run(build_app(preset=preset, max_new_tokens=new_tokens,
                                max_batch_size=max(8, concurrency)),
                      name="llmbench", route_prefix="/llmbench")
        h.remote({"prompt": "warmup"}).result(timeout=600)  # compile

        lock = threading.Lock()
        done = {"started": 0, "ok": 0, "errors": 0}

        def client(k):
            while True:
                with lock:
                    if done["started"] >= requests_total:
                        return
                    done["started"] += 1
                try:
                    h.remote({"prompt": f"request {k}"}).result(timeout=600)
                    with lock:
                        done["ok"] += 1
                except Exception:
                    with lock:
                        done["errors"] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        n_ok = done["ok"]
        return {
            "requests": n_ok,
            "errors": done["errors"],
            "concurrency": concurrency,
            "requests_per_sec": round(n_ok / dt, 2),
            "serve_decode_tokens_per_sec": round(n_ok * new_tokens / dt, 1),
            "elapsed_s": round(dt, 2),
        }
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- loadgen


def _probe_provenance(log) -> dict:
    """bench.py's shared provenance helper (one definition for every
    harness; a missing bench.py still yields an honest tpu_lost record)."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench import probe_provenance

        return probe_provenance(log)
    except Exception as e:
        log(f"provenance helper unavailable ({e!r}); treating as lost")
        return {"tpu_probe_ok": False, "tpu_probe_attempts": 0,
                "tpu_lost": True, "forced_cpu": False,
                "device": "unknown", "device_kind": "unknown"}


def _percentiles(xs, unit_scale=1e3):
    import numpy as np

    if not xs:
        return {"p50": None, "p99": None}
    a = np.asarray(xs, float) * unit_scale
    return {"p50": round(float(np.percentile(a, 50)), 2),
            "p99": round(float(np.percentile(a, 99)), 2)}


def _make_load(seed: int, n: int, rate_rps: float, new_tokens_cap: int):
    """The offered load: Poisson arrivals, mixed prompt lengths, heavy-
    tailed (Pareto) per-request generation budgets — the shape that makes
    flush-and-drain batching pathological (one long request pins its whole
    flush; queued requests wait a full generation)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    lens = rng.choice([4, 12, 24, 40], size=n, p=[0.35, 0.35, 0.2, 0.1])
    letters = "abcdefghijklmnopqrstuvwxyz"
    prompts = ["".join(rng.choice(list(letters), size=int(L)))
               for L in lens]
    budgets = [int(min(new_tokens_cap, 1 + round(4 * rng.pareto(1.5))))
               for _ in range(n)]
    return list(zip(arrivals.tolist(), prompts, budgets))


def _make_prefix_load(seed: int, n: int, rate_rps: float,
                      new_tokens_cap: int, *, n_prefixes: int = 8,
                      prefix_len: int = 224, zipf_s: float = 1.1,
                      max_seq_len: int = 256):
    """ISSUE-13 shared-prefix workload: a handful of long system-prompt /
    few-shot preambles chosen Zipf-distributed (a few preambles dominate,
    the tail is cold — real multi-tenant traffic shape), each followed by
    a short unique per-request tail. Prefix reuse is the whole game here:
    a scheduler that re-prefills every preamble burns ~prefix_len tokens
    of compute per request that a radix cache turns into a page-table
    splice."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    letters = "abcdefghijklmnopqrstuvwxyz "
    prefixes = ["".join(rng.choice(list(letters), size=prefix_len))
                for _ in range(n_prefixes)]
    ranks = np.arange(1, n_prefixes + 1, dtype=float)
    p = ranks ** (-zipf_s)
    p /= p.sum()
    which = rng.choice(n_prefixes, size=n, p=p)
    tail_lens = rng.integers(4, 11, size=n)
    prompts = [prefixes[w] + f"{i:03d}" +
               "".join(rng.choice(list(letters), size=int(t)))
               for i, (w, t) in enumerate(zip(which, tail_lens))]
    # prompt + budget must fit the (possibly overridden) context window
    # regardless of the mixed-workload cap
    cap = min(new_tokens_cap, max_seq_len - (prefix_len + 3 + 10) - 2)
    cap = max(2, min(cap, 12))
    budgets = [int(min(cap, 2 + round(3 * rng.pareto(1.5))))
               for _ in range(n)]
    return list(zip(arrivals.tolist(), prompts, budgets))


async def _drive_open_loop(server, load, streaming: bool):
    """Replay the arrival schedule against one replica callable. Streaming
    consumption measures true TTFT/inter-token latency; non-streaming
    (the flush-and-drain baseline delivers every token at completion)
    records completion time as the first-token time — which IS that
    path's honest TTFT."""
    results = []
    loop = asyncio.get_running_loop()
    t_start = loop.time()

    async def one(at, prompt, budget):
        await asyncio.sleep(max(0.0, t_start + at - loop.time()))
        t0 = time.perf_counter()
        times = []
        if streaming:
            gen = await server({"prompt": prompt, "stream": True,
                                "max_new_tokens": budget})
            async for _chunk in gen:
                times.append(time.perf_counter())
        else:
            out = await server({"prompt": prompt,
                                "max_new_tokens": budget})
            times = [time.perf_counter()] * out["num_tokens"]
        results.append({"t0": t0, "times": times})

    await asyncio.gather(*[one(*req) for req in load])
    wall = max(r["times"][-1] for r in results) - min(
        r["t0"] for r in results)
    ttfts = [r["times"][0] - r["t0"] for r in results]
    itls = [b - a for r in results if streaming
            for a, b in zip(r["times"], r["times"][1:])]
    tokens = sum(len(r["times"]) for r in results)
    return {"wall_s": round(wall, 3), "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1),
            "requests": len(results),
            "ttft_ms": _percentiles(ttfts),
            "inter_token_ms": _percentiles(itls)}


def run_loadgen(mode: str, preset: str, rate_rps: float, n: int, seed: int,
                *, slots: int = 8, prefill_chunk: int = 16,
                new_tokens_cap: int = 48, workload: str = "mixed",
                kv_layout: str = "contiguous",
                prefix_cache: bool = False,
                prefix_len: int = 224, max_seq_len: int = 256,
                kv_pages: int = 0) -> dict:
    """One open-loop run against a directly-instantiated replica callable
    (the serve path minus transport: scheduler + jitted programs — what
    the ISSUE-9/13 comparisons are about). mode: "continuous" | "batch";
    workload: "mixed" (ISSUE 9) | "prefix" (ISSUE 13 Zipf shared-prefix);
    kv_layout/prefix_cache select the paged arena + radix cache vs the
    PR-9 contiguous arena (continuous mode only)."""
    from ray_tpu.serve.llm import LLMServerImpl

    kw = {}
    if mode == "continuous":
        kw = {"kv_layout": kv_layout,
              "prefix_cache": prefix_cache if kv_layout == "paged" else None}
        if kv_layout == "paged" and kv_pages:
            kw["kv_pages"] = kv_pages
    if workload == "prefix":
        # the shared preambles need a context window wider than the debug
        # preset's 128 (production few-shot preambles dwarf the tails);
        # every candidate gets the same window
        kw["preset_overrides"] = {"max_seq_len": max_seq_len}
    server = LLMServerImpl(
        preset=preset, max_new_tokens=new_tokens_cap, scheduler=mode,
        slots=slots, prefill_chunk=prefill_chunk, share_weights=False,
        max_batch_size=slots, **kw)
    try:
        if workload == "prefix":
            load = _make_prefix_load(seed, n, rate_rps, new_tokens_cap,
                                     prefix_len=prefix_len,
                                     max_seq_len=max_seq_len)
        else:
            load = _make_load(seed, n, rate_rps, new_tokens_cap)
        # warmup = a full replay of the SAME load, off the clock: the
        # request-level baseline compiles one program per (batch, length,
        # steps) shape its flushes happen to form — measuring its shape-
        # churn compiles would flatter the continuous path (which compiles
        # exactly two programs) for the wrong reason on CPU. For the
        # prefix-cache comparison the warm replay also PRE-POPULATES the
        # radix cache for both candidates symmetrically (the measured run
        # sees the steady-state hit rate, not the cold ramp)
        asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        warm = (server.scheduler_stats()
                if mode == "continuous" else {})
        out = asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        out["scheduler"] = server.scheduler_stats()
        if mode == "continuous":
            st = out["scheduler"]
            # fallback guard: the ITERATION-LEVEL path must have engaged —
            # a silent fall-back to flush-and-drain cannot vacuously pass
            assert st["mode"] == "continuous", st
            assert st["admitted_mid_flight"] > 0, (
                "no request was admitted mid-generation; the open-loop "
                f"load never exercised continuous batching: {st}")
            assert st["kv_layout"] == kv_layout, st
            if prefix_cache and kv_layout == "paged":
                # fallback guard: the radix cache must actually have
                # spliced prefixes, and exactly two programs compiled
                assert st["prefix_hits"] > 0, (
                    f"prefix cache never hit on the shared-prefix load: "
                    f"{st}")
                assert st["compiled_programs"] == 2, st
                # steady-state hit rate: the MEASURED run's delta only
                # (the warmup replay exists precisely to absorb the
                # cold-ramp misses — don't blend them back in)
                dh = st["prefix_hits"] - warm.get("prefix_hits", 0)
                dm = st["prefix_misses"] - warm.get("prefix_misses", 0)
                out["prefix_hit_rate"] = round(dh / max(dh + dm, 1), 4)
        return out
    finally:
        server.shutdown()


def loadgen_main(args) -> None:
    log = lambda m: print(f"bench_serve: {m}", file=sys.stderr)  # noqa: E731
    prov = _probe_provenance(log)
    common = dict(slots=args.slots, new_tokens_cap=args.new_tokens_cap,
                  prefill_chunk=args.prefill_chunk,
                  prefix_len=args.prefix_len,
                  max_seq_len=args.max_seq_len)
    base_detail = {"requests": args.requests, "seed": args.seed,
                   "slots": args.slots, "preset": args.preset,
                   "new_tokens_cap": args.new_tokens_cap,
                   "arrivals": "poisson"}
    records = []

    # ---- ISSUE-13: Zipf shared-prefix workload, three-way ----
    # paged arena + radix prefix cache vs the PR-9 continuous arena vs
    # request-level batching, same offered load (saturating, so tokens/s
    # measures CAPACITY, not the arrival rate). The paged pool gets
    # headroom for the radix working set (the 8 preambles stay resident)
    # on top of the slots' demand — that residency IS the mechanism being
    # measured; the scheduler stats in the detail show what it held
    from ray_tpu._private.config import global_config

    pt = global_config().serve_page_tokens  # the scheduler's actual size
    pool = (args.slots * (args.max_seq_len // pt)
            + 8 * (args.prefix_len // pt) + 1)
    pfx_detail = {**base_detail, "workload": "prefix",
                  "rate_rps": args.prefix_rate,
                  "max_seq_len": args.max_seq_len,
                  "new_tokens_dist": "2+3*pareto(1.5), capped at 12",
                  "prefix_dist": (
                      f"zipf(s=1.1) over 8 x {args.prefix_len}-token "
                      f"preambles, 4-10-token tails")}
    log("paged+prefix continuous (zipf shared-prefix workload) ...")
    paged = run_loadgen("continuous", args.preset, args.prefix_rate,
                        args.requests, args.seed, workload="prefix",
                        kv_layout="paged", prefix_cache=True,
                        kv_pages=pool, **common)
    log("PR-9 contiguous continuous (zipf shared-prefix workload) ...")
    cont_p = run_loadgen("continuous", args.preset, args.prefix_rate,
                         args.requests, args.seed, workload="prefix",
                         kv_layout="contiguous", **common)
    log("request-level batch (zipf shared-prefix workload) ...")
    base_p = run_loadgen("batch", args.preset, args.prefix_rate,
                         args.requests, args.seed, workload="prefix",
                         **common)
    paged_speedup = paged["tokens_per_sec"] / max(
        cont_p["tokens_per_sec"], 1e-9)
    records += [
        {"metric": "serve_loadgen_paged_prefix_tokens_per_sec",
         "value": paged["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**paged, **pfx_detail, **prov}},
        {"metric": "serve_loadgen_continuous_prefix_tokens_per_sec",
         "value": cont_p["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**cont_p, **pfx_detail, **prov}},
        {"metric": "serve_loadgen_request_batch_prefix_tokens_per_sec",
         "value": base_p["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**base_p, **pfx_detail, **prov}},
        {"metric": "serve_paged_prefix_speedup",
         "value": round(paged_speedup, 2), "unit": "x",
         "detail": {"vs": "PR-9 contiguous continuous, same offered load",
                    "prefix_hit_rate": paged.get("prefix_hit_rate"),
                    # arena accounting, auditable from the record alone:
                    # the paged pool carries the radix working set ON TOP
                    # of the slots' demand — that residency is the
                    # mechanism being measured, not hidden headroom
                    "paged_pool_pages": paged["scheduler"]["num_pages"],
                    "paged_page_tokens":
                        paged["scheduler"]["page_tokens"],
                    "paged_peak_pages_in_use":
                        paged["scheduler"]["peak_pages_in_use"],
                    "contiguous_arena_tokens":
                        args.slots * args.max_seq_len,
                    "paged_p99_ttft_ms": paged["ttft_ms"]["p99"],
                    "continuous_p99_ttft_ms": cont_p["ttft_ms"]["p99"],
                    "paged_p50_ttft_ms": paged["ttft_ms"]["p50"],
                    "continuous_p50_ttft_ms": cont_p["ttft_ms"]["p50"],
                    **pfx_detail, **prov}},
    ]

    # ---- ISSUE-9 continuity: mixed workload, continuous vs batch ----
    # (the PR-9 record, re-measured on the PR-9 contiguous arena: the
    # mixed-length heavy-tail load where iteration-level scheduling wins;
    # uniform near-window-length prompts would instead flatter the
    # whole-prompt-prefill batch path)
    mix_detail = {**base_detail, "workload": "mixed",
                  "rate_rps": args.rate,
                  "new_tokens_dist": "1+4*pareto(1.5), capped"}
    log("PR-9 contiguous continuous (mixed workload) ...")
    cont = run_loadgen("continuous", args.preset, args.rate, args.requests,
                       args.seed, workload="mixed",
                       kv_layout="contiguous", **common)
    log("request-level batch baseline (mixed workload) ...")
    base = run_loadgen("batch", args.preset, args.rate, args.requests,
                       args.seed, workload="mixed", **common)
    speedup = cont["tokens_per_sec"] / max(base["tokens_per_sec"], 1e-9)
    ttft_ratio = (base["ttft_ms"]["p99"] or 0.0) / max(
        cont["ttft_ms"]["p99"] or 1e-9, 1e-9)
    records += [
        {"metric": "serve_loadgen_continuous_tokens_per_sec",
         "value": cont["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**cont, **mix_detail, **prov}},
        {"metric": "serve_loadgen_request_batch_tokens_per_sec",
         "value": base["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**base, **mix_detail, **prov}},
        {"metric": "serve_continuous_speedup",
         "value": round(speedup, 2), "unit": "x",
         "detail": {"p99_ttft_improvement_x": round(ttft_ratio, 2),
                    "continuous_p99_ttft_ms": cont["ttft_ms"]["p99"],
                    "baseline_p99_ttft_ms": base["ttft_ms"]["p99"],
                    "continuous_p50_ttft_ms": cont["ttft_ms"]["p50"],
                    "baseline_p50_ttft_ms": base["ttft_ms"]["p50"],
                    **mix_detail, **prov}},
    ]
    for rec in records:
        print(json.dumps(rec))
    if args.json_out:
        doc = {
            "suite": "serve_llm_continuous_batching",
            "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
            "host": __import__("platform").platform(),
            "provenance": prov,
            "records": records,
        }
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2_small")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--serve", action="store_true",
                    help="drive the full Serve deployment (continuous "
                         "batching) instead of the raw decode program")
    ap.add_argument("--loadgen", action="store_true",
                    help="open-loop load generator: continuous vs "
                         "request-level batching at the same offered load")
    ap.add_argument("--rate", type=float, default=75.0,
                    help="mixed-workload Poisson arrival rate (req/s); the "
                         "default saturates the request-level baseline "
                         "on a CPU host so the capacity gap is visible")
    ap.add_argument("--prefix-rate", type=float, default=600.0,
                    help="shared-prefix-workload arrival rate (req/s); "
                         "must saturate BOTH continuous schedulers so "
                         "tokens/s measures capacity, not arrivals")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--new-tokens-cap", type=int, default=48)
    ap.add_argument("--prefill-chunk", type=int, default=16,
                    help="scheduler prefill chunk width (both continuous "
                         "candidates)")
    ap.add_argument("--prefix-len", type=int, default=224,
                    help="shared preamble length (tokens) for the prefix "
                         "workload")
    ap.add_argument("--max-seq-len", type=int, default=256,
                    help="context-window override for the prefix workload "
                         "(preamble + tail + budget must fit)")
    ap.add_argument("--json-out", default="",
                    help="also write the full loadgen suite to this file")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default: 150 loadgen, 64 serve)")
    args = ap.parse_args(argv)
    if args.requests is None:
        args.requests = 150 if args.loadgen else 64

    if args.loadgen:
        if args.preset == "gpt2_small":
            args.preset = "llama_debug"  # loadgen default: runnable anywhere
        loadgen_main(args)
        return

    if args.serve:
        detail = bench_serve_path(args.preset, args.new_tokens,
                                  args.concurrency, args.requests)
        print(json.dumps({
            "metric": "serve_llm_decode_tokens_per_sec",
            "value": detail["serve_decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": round(
                detail["serve_decode_tokens_per_sec"] / 1000.0, 4),
            "detail": dict(detail, preset=args.preset,
                           new_tokens=args.new_tokens),
        }))
        return

    import jax

    detail = bench_decode(args.preset, args.prompt_len, args.new_tokens)
    best = max(detail["per_batch"],
               key=lambda r: r["decode_tokens_per_sec"])
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec",
        "value": best["decode_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(best["decode_tokens_per_sec"] / 1000.0, 4),
        "detail": dict(detail,
                       device=str(getattr(jax.devices()[0], "device_kind",
                                          "cpu"))),
    }))


if __name__ == "__main__":
    main()
