"""Serve-decode throughput harness: batched autoregressive decode on the
local chip (the BASELINE "Serve-equivalent LLM deployment ... batched
replica throughput" row) plus the ISSUE-9 open-loop load generator.

Modes:

  * default — the jitted prefill + per-token decode loop from
    `ray_tpu.models.decode` across batch sizes (raw device decode
    capacity);
  * --serve — end-to-end through a live Serve deployment (router ->
    replica -> continuous scheduler);
  * --loadgen — OPEN-LOOP load generator against the replica serve path:
    Poisson arrivals, mixed prompt lengths, heavy-tailed per-request
    `max_new_tokens`; drives BOTH the continuous (iteration-level)
    scheduler and the request-level `@serve.batch` baseline at the same
    offered load and reports p50/p99 TTFT, p50/p99 inter-token latency,
    and useful tokens/s for each, plus the continuous/baseline ratios.
    Records carry the PR-6 TPU-probe provenance fields (`tpu_lost`,
    `tpu_probe_ok`, `tpu_probe_attempts`, `device`) so CPU-smoke numbers
    are distinguishable from regressions.

    python bench_serve.py --loadgen [--rate 20] [--requests 60]
                          [--seed 0] [--json-out SERVE_BENCH.json]

vs_baseline of the default mode is decode tokens/s at the best batch
divided by 1000 (a single-GPU 7B-class continuous-batching serving rate
is O(1000) tok/s; the debug-size model here is smaller, so treat it as a
scale probe, not a model-for-model comparison).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time


def bench_decode(preset: str, prompt_len: int, new_tokens: int,
                 batches=(1, 8, 32)) -> dict:
    import functools

    import jax

    from ray_tpu.models import presets
    from ray_tpu.models.decode import generate
    from ray_tpu.models.transformer import init_params

    cfg = getattr(presets, preset)()
    params = init_params(cfg, jax.random.PRNGKey(0))
    # one compiled program per batch size: prefill + lax.scan over decode
    # steps — the replica-side program shape (per-token host dispatch
    # through the test tunnel would measure the tunnel, not the chip)
    gen = jax.jit(functools.partial(generate, cfg,
                                    max_new_tokens=new_tokens),
                  static_argnames=())

    results = []
    for batch in batches:
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        key = jax.random.PRNGKey(2)
        toks = gen(params, tokens, key)
        float(toks.sum())  # compile + warmup, host sync
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            toks = gen(params, tokens, key)
        float(toks.sum())
        dt = (time.perf_counter() - t0) / iters
        decode_tps = batch * new_tokens / dt
        results.append({
            "batch": batch,
            "decode_tokens_per_sec": round(decode_tps, 1),
            "latency_ms_per_token": round(dt / new_tokens * 1e3, 2),
            "end_to_end_s": round(dt, 3),
        })
    return {"per_batch": results, "preset": preset,
            "prompt_len": prompt_len, "new_tokens": new_tokens}


def bench_serve_path(preset: str, new_tokens: int, concurrency: int,
                     requests_total: int) -> dict:
    """End-to-end CONTINUOUS-BATCHING measurement: concurrent requests
    through a live Serve deployment (router -> replica -> @serve.batch
    coalescing -> one batched generate per flush), tokens/s counted at
    the client. This is the serving number; `bench_decode` is the raw
    device decode capacity it converges to as batching amortizes."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_app

    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    try:
        h = serve.run(build_app(preset=preset, max_new_tokens=new_tokens,
                                max_batch_size=max(8, concurrency)),
                      name="llmbench", route_prefix="/llmbench")
        h.remote({"prompt": "warmup"}).result(timeout=600)  # compile

        lock = threading.Lock()
        done = {"started": 0, "ok": 0, "errors": 0}

        def client(k):
            while True:
                with lock:
                    if done["started"] >= requests_total:
                        return
                    done["started"] += 1
                try:
                    h.remote({"prompt": f"request {k}"}).result(timeout=600)
                    with lock:
                        done["ok"] += 1
                except Exception:
                    with lock:
                        done["errors"] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        n_ok = done["ok"]
        return {
            "requests": n_ok,
            "errors": done["errors"],
            "concurrency": concurrency,
            "requests_per_sec": round(n_ok / dt, 2),
            "serve_decode_tokens_per_sec": round(n_ok * new_tokens / dt, 1),
            "elapsed_s": round(dt, 2),
        }
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- loadgen


def _probe_provenance(log) -> dict:
    """bench.py's shared provenance helper (one definition for every
    harness; a missing bench.py still yields an honest tpu_lost record)."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench import probe_provenance

        return probe_provenance(log)
    except Exception as e:
        log(f"provenance helper unavailable ({e!r}); treating as lost")
        return {"tpu_probe_ok": False, "tpu_probe_attempts": 0,
                "tpu_lost": True, "forced_cpu": False,
                "device": "unknown", "device_kind": "unknown"}


def _percentiles(xs, unit_scale=1e3):
    import numpy as np

    if not xs:
        return {"p50": None, "p99": None}
    a = np.asarray(xs, float) * unit_scale
    return {"p50": round(float(np.percentile(a, 50)), 2),
            "p99": round(float(np.percentile(a, 99)), 2)}


def _make_load(seed: int, n: int, rate_rps: float, new_tokens_cap: int):
    """The offered load: Poisson arrivals, mixed prompt lengths, heavy-
    tailed (Pareto) per-request generation budgets — the shape that makes
    flush-and-drain batching pathological (one long request pins its whole
    flush; queued requests wait a full generation)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    lens = rng.choice([4, 12, 24, 40], size=n, p=[0.35, 0.35, 0.2, 0.1])
    letters = "abcdefghijklmnopqrstuvwxyz"
    prompts = ["".join(rng.choice(list(letters), size=int(L)))
               for L in lens]
    budgets = [int(min(new_tokens_cap, 1 + round(4 * rng.pareto(1.5))))
               for _ in range(n)]
    return list(zip(arrivals.tolist(), prompts, budgets))


async def _drive_open_loop(server, load, streaming: bool):
    """Replay the arrival schedule against one replica callable. Streaming
    consumption measures true TTFT/inter-token latency; non-streaming
    (the flush-and-drain baseline delivers every token at completion)
    records completion time as the first-token time — which IS that
    path's honest TTFT."""
    results = []
    loop = asyncio.get_running_loop()
    t_start = loop.time()

    async def one(at, prompt, budget):
        await asyncio.sleep(max(0.0, t_start + at - loop.time()))
        t0 = time.perf_counter()
        times = []
        if streaming:
            gen = await server({"prompt": prompt, "stream": True,
                                "max_new_tokens": budget})
            async for _chunk in gen:
                times.append(time.perf_counter())
        else:
            out = await server({"prompt": prompt,
                                "max_new_tokens": budget})
            times = [time.perf_counter()] * out["num_tokens"]
        results.append({"t0": t0, "times": times})

    await asyncio.gather(*[one(*req) for req in load])
    wall = max(r["times"][-1] for r in results) - min(
        r["t0"] for r in results)
    ttfts = [r["times"][0] - r["t0"] for r in results]
    itls = [b - a for r in results if streaming
            for a, b in zip(r["times"], r["times"][1:])]
    tokens = sum(len(r["times"]) for r in results)
    return {"wall_s": round(wall, 3), "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1),
            "requests": len(results),
            "ttft_ms": _percentiles(ttfts),
            "inter_token_ms": _percentiles(itls)}


def run_loadgen(mode: str, preset: str, rate_rps: float, n: int, seed: int,
                *, slots: int = 8, prefill_chunk: int = 16,
                new_tokens_cap: int = 48) -> dict:
    """One open-loop run against a directly-instantiated replica callable
    (the serve path minus transport: scheduler + jitted programs — what
    the ISSUE-9 comparison is about). mode: "continuous" | "batch"."""
    from ray_tpu.serve.llm import LLMServerImpl

    server = LLMServerImpl(
        preset=preset, max_new_tokens=new_tokens_cap, scheduler=mode,
        slots=slots, prefill_chunk=prefill_chunk, share_weights=False,
        max_batch_size=slots)
    try:
        load = _make_load(seed, n, rate_rps, new_tokens_cap)
        # warmup = a full replay of the SAME load, off the clock: the
        # request-level baseline compiles one program per (batch, length,
        # steps) shape its flushes happen to form — measuring its shape-
        # churn compiles would flatter the continuous path (which compiles
        # exactly two programs) for the wrong reason on CPU
        asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        out = asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        out["scheduler"] = server.scheduler_stats()
        if mode == "continuous":
            st = out["scheduler"]
            # fallback guard: the ITERATION-LEVEL path must have engaged —
            # a silent fall-back to flush-and-drain cannot vacuously pass
            assert st["mode"] == "continuous", st
            assert st["admitted_mid_flight"] > 0, (
                "no request was admitted mid-generation; the open-loop "
                f"load never exercised continuous batching: {st}")
        return out
    finally:
        server.shutdown()


def loadgen_main(args) -> None:
    log = lambda m: print(f"bench_serve: {m}", file=sys.stderr)  # noqa: E731
    prov = _probe_provenance(log)
    cont = run_loadgen("continuous", args.preset, args.rate, args.requests,
                       args.seed, slots=args.slots,
                       new_tokens_cap=args.new_tokens_cap)
    base = run_loadgen("batch", args.preset, args.rate, args.requests,
                       args.seed, slots=args.slots,
                       new_tokens_cap=args.new_tokens_cap)
    speedup = cont["tokens_per_sec"] / max(base["tokens_per_sec"], 1e-9)
    ttft_ratio = (base["ttft_ms"]["p99"] or 0.0) / max(
        cont["ttft_ms"]["p99"] or 1e-9, 1e-9)
    load_detail = {"rate_rps": args.rate, "requests": args.requests,
                   "seed": args.seed, "slots": args.slots,
                   "preset": args.preset,
                   "new_tokens_cap": args.new_tokens_cap,
                   "arrivals": "poisson",
                   "new_tokens_dist": "1+4*pareto(1.5), capped"}
    records = [
        {"metric": "serve_loadgen_continuous_tokens_per_sec",
         "value": cont["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**cont, **load_detail, **prov}},
        {"metric": "serve_loadgen_request_batch_tokens_per_sec",
         "value": base["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**base, **load_detail, **prov}},
        {"metric": "serve_continuous_speedup",
         "value": round(speedup, 2), "unit": "x",
         "detail": {"p99_ttft_improvement_x": round(ttft_ratio, 2),
                    "continuous_p99_ttft_ms": cont["ttft_ms"]["p99"],
                    "baseline_p99_ttft_ms": base["ttft_ms"]["p99"],
                    "continuous_p50_ttft_ms": cont["ttft_ms"]["p50"],
                    "baseline_p50_ttft_ms": base["ttft_ms"]["p50"],
                    **load_detail, **prov}},
    ]
    for rec in records:
        print(json.dumps(rec))
    if args.json_out:
        doc = {
            "suite": "serve_llm_continuous_batching",
            "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
            "host": __import__("platform").platform(),
            "provenance": prov,
            "records": records,
        }
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2_small")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--serve", action="store_true",
                    help="drive the full Serve deployment (continuous "
                         "batching) instead of the raw decode program")
    ap.add_argument("--loadgen", action="store_true",
                    help="open-loop load generator: continuous vs "
                         "request-level batching at the same offered load")
    ap.add_argument("--rate", type=float, default=75.0,
                    help="loadgen Poisson arrival rate (req/s); the "
                         "default saturates the request-level baseline "
                         "on a CPU host so the capacity gap is visible")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--new-tokens-cap", type=int, default=48)
    ap.add_argument("--json-out", default="",
                    help="also write the full loadgen suite to this file")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default: 150 loadgen, 64 serve)")
    args = ap.parse_args(argv)
    if args.requests is None:
        args.requests = 150 if args.loadgen else 64

    if args.loadgen:
        if args.preset == "gpt2_small":
            args.preset = "llama_debug"  # loadgen default: runnable anywhere
        loadgen_main(args)
        return

    if args.serve:
        detail = bench_serve_path(args.preset, args.new_tokens,
                                  args.concurrency, args.requests)
        print(json.dumps({
            "metric": "serve_llm_decode_tokens_per_sec",
            "value": detail["serve_decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": round(
                detail["serve_decode_tokens_per_sec"] / 1000.0, 4),
            "detail": dict(detail, preset=args.preset,
                           new_tokens=args.new_tokens),
        }))
        return

    import jax

    detail = bench_decode(args.preset, args.prompt_len, args.new_tokens)
    best = max(detail["per_batch"],
               key=lambda r: r["decode_tokens_per_sec"])
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec",
        "value": best["decode_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(best["decode_tokens_per_sec"] / 1000.0, 4),
        "detail": dict(detail,
                       device=str(getattr(jax.devices()[0], "device_kind",
                                          "cpu"))),
    }))


if __name__ == "__main__":
    main()
