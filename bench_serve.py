"""Serve-decode throughput harness: batched autoregressive decode on the
local chip (the BASELINE "Serve-equivalent LLM deployment ... batched
replica throughput" row).

Measures the jitted prefill + per-token decode loop from
`ray_tpu.models.decode` — the exact program a Serve LLM replica runs per
`@serve.batch` flush (serve/llm.py) — across batch sizes, and prints ONE
JSON line with the peak batched decode rate:

    python bench_serve.py [--preset gpt2_small] [--prompt-len 128]
                          [--new-tokens 64]

vs_baseline is decode tokens/s at the best batch divided by 1000 (a
single-GPU 7B-class continuous-batching serving rate is O(1000) tok/s;
the debug-size model here is smaller, so treat it as a scale probe, not
a model-for-model comparison).
"""

from __future__ import annotations

import argparse
import json
import time


def bench_decode(preset: str, prompt_len: int, new_tokens: int,
                 batches=(1, 8, 32)) -> dict:
    import functools

    import jax

    from ray_tpu.models import presets
    from ray_tpu.models.decode import generate
    from ray_tpu.models.transformer import init_params

    cfg = getattr(presets, preset)()
    params = init_params(cfg, jax.random.PRNGKey(0))
    # one compiled program per batch size: prefill + lax.scan over decode
    # steps — the replica-side program shape (per-token host dispatch
    # through the test tunnel would measure the tunnel, not the chip)
    gen = jax.jit(functools.partial(generate, cfg,
                                    max_new_tokens=new_tokens),
                  static_argnames=())

    results = []
    for batch in batches:
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        key = jax.random.PRNGKey(2)
        toks = gen(params, tokens, key)
        float(toks.sum())  # compile + warmup, host sync
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            toks = gen(params, tokens, key)
        float(toks.sum())
        dt = (time.perf_counter() - t0) / iters
        decode_tps = batch * new_tokens / dt
        results.append({
            "batch": batch,
            "decode_tokens_per_sec": round(decode_tps, 1),
            "latency_ms_per_token": round(dt / new_tokens * 1e3, 2),
            "end_to_end_s": round(dt, 3),
        })
    return {"per_batch": results, "preset": preset,
            "prompt_len": prompt_len, "new_tokens": new_tokens}


def bench_serve_path(preset: str, new_tokens: int, concurrency: int,
                     requests_total: int) -> dict:
    """End-to-end CONTINUOUS-BATCHING measurement: concurrent requests
    through a live Serve deployment (router -> replica -> @serve.batch
    coalescing -> one batched generate per flush), tokens/s counted at
    the client. This is the serving number; `bench_decode` is the raw
    device decode capacity it converges to as batching amortizes."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_app

    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    try:
        h = serve.run(build_app(preset=preset, max_new_tokens=new_tokens,
                                max_batch_size=max(8, concurrency)),
                      name="llmbench", route_prefix="/llmbench")
        h.remote({"prompt": "warmup"}).result(timeout=600)  # compile

        lock = threading.Lock()
        done = {"started": 0, "ok": 0, "errors": 0}

        def client(k):
            while True:
                with lock:
                    if done["started"] >= requests_total:
                        return
                    done["started"] += 1
                try:
                    h.remote({"prompt": f"request {k}"}).result(timeout=600)
                    with lock:
                        done["ok"] += 1
                except Exception:
                    with lock:
                        done["errors"] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        n_ok = done["ok"]
        return {
            "requests": n_ok,
            "errors": done["errors"],
            "concurrency": concurrency,
            "requests_per_sec": round(n_ok / dt, 2),
            "serve_decode_tokens_per_sec": round(n_ok * new_tokens / dt, 1),
            "elapsed_s": round(dt, 2),
        }
    finally:
        ray_tpu.shutdown()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2_small")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--serve", action="store_true",
                    help="drive the full Serve deployment (continuous "
                         "batching) instead of the raw decode program")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--requests", type=int, default=64)
    args = ap.parse_args(argv)

    if args.serve:
        detail = bench_serve_path(args.preset, args.new_tokens,
                                  args.concurrency, args.requests)
        print(json.dumps({
            "metric": "serve_llm_decode_tokens_per_sec",
            "value": detail["serve_decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": round(
                detail["serve_decode_tokens_per_sec"] / 1000.0, 4),
            "detail": dict(detail, preset=args.preset,
                           new_tokens=args.new_tokens),
        }))
        return

    import jax

    detail = bench_decode(args.preset, args.prompt_len, args.new_tokens)
    best = max(detail["per_batch"],
               key=lambda r: r["decode_tokens_per_sec"])
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec",
        "value": best["decode_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(best["decode_tokens_per_sec"] / 1000.0, 4),
        "detail": dict(detail,
                       device=str(getattr(jax.devices()[0], "device_kind",
                                          "cpu"))),
    }))


if __name__ == "__main__":
    main()
