"""Serve-decode throughput harness: batched autoregressive decode on the
local chip (the BASELINE "Serve-equivalent LLM deployment ... batched
replica throughput" row) plus the ISSUE-9 open-loop load generator.

Modes:

  * default — the jitted prefill + per-token decode loop from
    `ray_tpu.models.decode` across batch sizes (raw device decode
    capacity);
  * --serve — end-to-end through a live Serve deployment (router ->
    replica -> continuous scheduler);
  * --loadgen — OPEN-LOOP load generator against the replica serve path:
    Poisson arrivals; `--workload prefix` (default, ISSUE 13) draws each
    prompt as a Zipf-distributed shared preamble (8 x 224-token system
    prompts / few-shot preambles) plus a unique 4-10-token tail, while
    `--workload mixed` keeps the ISSUE-9 mixed-length/heavy-tail shape.
    Drives THREE schedulers at the same offered load — paged arena +
    radix prefix cache, the PR-9 contiguous continuous arena, and the
    request-level `@serve.batch` baseline — and reports p50/p99 TTFT,
    p50/p99 inter-token latency, useful tokens/s and `prefix_hit_rate`,
    plus the paged/continuous and continuous/baseline ratios. Records
    carry the PR-6 TPU-probe provenance fields (`tpu_lost`,
    `tpu_probe_ok`, `tpu_probe_attempts`, `device`) so CPU-smoke numbers
    are distinguishable from regressions.

    python bench_serve.py --loadgen [--rate 20] [--requests 60]
                          [--seed 0] [--json-out SERVE_BENCH.json]

vs_baseline of the default mode is decode tokens/s at the best batch
divided by 1000 (a single-GPU 7B-class continuous-batching serving rate
is O(1000) tok/s; the debug-size model here is smaller, so treat it as a
scale probe, not a model-for-model comparison).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time


def bench_decode(preset: str, prompt_len: int, new_tokens: int,
                 batches=(1, 8, 32)) -> dict:
    import functools

    import jax

    from ray_tpu.models import presets
    from ray_tpu.models.decode import generate
    from ray_tpu.models.transformer import init_params

    cfg = getattr(presets, preset)()
    params = init_params(cfg, jax.random.PRNGKey(0))
    # one compiled program per batch size: prefill + lax.scan over decode
    # steps — the replica-side program shape (per-token host dispatch
    # through the test tunnel would measure the tunnel, not the chip)
    gen = jax.jit(functools.partial(generate, cfg,
                                    max_new_tokens=new_tokens),
                  static_argnames=())

    results = []
    for batch in batches:
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        key = jax.random.PRNGKey(2)
        toks = gen(params, tokens, key)
        float(toks.sum())  # compile + warmup, host sync
        t0 = time.perf_counter()
        iters = 5
        for _ in range(iters):
            toks = gen(params, tokens, key)
        float(toks.sum())
        dt = (time.perf_counter() - t0) / iters
        decode_tps = batch * new_tokens / dt
        results.append({
            "batch": batch,
            "decode_tokens_per_sec": round(decode_tps, 1),
            "latency_ms_per_token": round(dt / new_tokens * 1e3, 2),
            "end_to_end_s": round(dt, 3),
        })
    return {"per_batch": results, "preset": preset,
            "prompt_len": prompt_len, "new_tokens": new_tokens}


def bench_serve_path(preset: str, new_tokens: int, concurrency: int,
                     requests_total: int) -> dict:
    """End-to-end CONTINUOUS-BATCHING measurement: concurrent requests
    through a live Serve deployment (router -> replica -> @serve.batch
    coalescing -> one batched generate per flush), tokens/s counted at
    the client. This is the serving number; `bench_decode` is the raw
    device decode capacity it converges to as batching amortizes."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu.serve.llm import build_app

    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    try:
        h = serve.run(build_app(preset=preset, max_new_tokens=new_tokens,
                                max_batch_size=max(8, concurrency)),
                      name="llmbench", route_prefix="/llmbench")
        h.remote({"prompt": "warmup"}).result(timeout=600)  # compile

        lock = threading.Lock()
        done = {"started": 0, "ok": 0, "errors": 0}

        def client(k):
            while True:
                with lock:
                    if done["started"] >= requests_total:
                        return
                    done["started"] += 1
                try:
                    h.remote({"prompt": f"request {k}"}).result(timeout=600)
                    with lock:
                        done["ok"] += 1
                except Exception:
                    with lock:
                        done["errors"] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        n_ok = done["ok"]
        return {
            "requests": n_ok,
            "errors": done["errors"],
            "concurrency": concurrency,
            "requests_per_sec": round(n_ok / dt, 2),
            "serve_decode_tokens_per_sec": round(n_ok * new_tokens / dt, 1),
            "elapsed_s": round(dt, 2),
        }
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- loadgen


def _probe_provenance(log) -> dict:
    """bench.py's shared provenance helper (one definition for every
    harness; a missing bench.py still yields an honest tpu_lost record)."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench import probe_provenance

        return probe_provenance(log)
    except Exception as e:
        log(f"provenance helper unavailable ({e!r}); treating as lost")
        return {"tpu_probe_ok": False, "tpu_probe_attempts": 0,
                "tpu_lost": True, "forced_cpu": False,
                "device": "unknown", "device_kind": "unknown"}


def _percentiles(xs, unit_scale=1e3):
    import numpy as np

    if not xs:
        return {"p50": None, "p99": None}
    a = np.asarray(xs, float) * unit_scale
    return {"p50": round(float(np.percentile(a, 50)), 2),
            "p99": round(float(np.percentile(a, 99)), 2)}


def _make_load(seed: int, n: int, rate_rps: float, new_tokens_cap: int):
    """The offered load: Poisson arrivals, mixed prompt lengths, heavy-
    tailed (Pareto) per-request generation budgets — the shape that makes
    flush-and-drain batching pathological (one long request pins its whole
    flush; queued requests wait a full generation)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    lens = rng.choice([4, 12, 24, 40], size=n, p=[0.35, 0.35, 0.2, 0.1])
    letters = "abcdefghijklmnopqrstuvwxyz"
    prompts = ["".join(rng.choice(list(letters), size=int(L)))
               for L in lens]
    budgets = [int(min(new_tokens_cap, 1 + round(4 * rng.pareto(1.5))))
               for _ in range(n)]
    return list(zip(arrivals.tolist(), prompts, budgets))


def _make_prefix_load(seed: int, n: int, rate_rps: float,
                      new_tokens_cap: int, *, n_prefixes: int = 8,
                      prefix_len: int = 224, zipf_s: float = 1.1,
                      max_seq_len: int = 256):
    """ISSUE-13 shared-prefix workload: a handful of long system-prompt /
    few-shot preambles chosen Zipf-distributed (a few preambles dominate,
    the tail is cold — real multi-tenant traffic shape), each followed by
    a short unique per-request tail. Prefix reuse is the whole game here:
    a scheduler that re-prefills every preamble burns ~prefix_len tokens
    of compute per request that a radix cache turns into a page-table
    splice."""
    import numpy as np

    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    letters = "abcdefghijklmnopqrstuvwxyz "
    prefixes = ["".join(rng.choice(list(letters), size=prefix_len))
                for _ in range(n_prefixes)]
    ranks = np.arange(1, n_prefixes + 1, dtype=float)
    p = ranks ** (-zipf_s)
    p /= p.sum()
    which = rng.choice(n_prefixes, size=n, p=p)
    tail_lens = rng.integers(4, 11, size=n)
    prompts = [prefixes[w] + f"{i:03d}" +
               "".join(rng.choice(list(letters), size=int(t)))
               for i, (w, t) in enumerate(zip(which, tail_lens))]
    # prompt + budget must fit the (possibly overridden) context window
    # regardless of the mixed-workload cap
    cap = min(new_tokens_cap, max_seq_len - (prefix_len + 3 + 10) - 2)
    cap = max(2, min(cap, 12))
    budgets = [int(min(cap, 2 + round(3 * rng.pareto(1.5))))
               for _ in range(n)]
    return list(zip(arrivals.tolist(), prompts, budgets))


async def _drive_open_loop(server, load, streaming: bool):
    """Replay the arrival schedule against one replica callable. Streaming
    consumption measures true TTFT/inter-token latency; non-streaming
    (the flush-and-drain baseline delivers every token at completion)
    records completion time as the first-token time — which IS that
    path's honest TTFT."""
    results = []
    loop = asyncio.get_running_loop()
    t_start = loop.time()

    async def one(at, prompt, budget):
        await asyncio.sleep(max(0.0, t_start + at - loop.time()))
        t0 = time.perf_counter()
        times = []
        if streaming:
            gen = await server({"prompt": prompt, "stream": True,
                                "max_new_tokens": budget})
            async for _chunk in gen:
                times.append(time.perf_counter())
        else:
            out = await server({"prompt": prompt,
                                "max_new_tokens": budget})
            times = [time.perf_counter()] * out["num_tokens"]
        results.append({"t0": t0, "times": times})

    await asyncio.gather(*[one(*req) for req in load])
    wall = max(r["times"][-1] for r in results) - min(
        r["t0"] for r in results)
    ttfts = [r["times"][0] - r["t0"] for r in results]
    itls = [b - a for r in results if streaming
            for a, b in zip(r["times"], r["times"][1:])]
    tokens = sum(len(r["times"]) for r in results)
    return {"wall_s": round(wall, 3), "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1),
            "requests": len(results),
            "ttft_ms": _percentiles(ttfts),
            "inter_token_ms": _percentiles(itls)}


def run_loadgen(mode: str, preset: str, rate_rps: float, n: int, seed: int,
                *, slots: int = 8, prefill_chunk: int = 16,
                new_tokens_cap: int = 48, workload: str = "mixed",
                kv_layout: str = "contiguous",
                prefix_cache: bool = False,
                prefix_len: int = 224, max_seq_len: int = 256,
                kv_pages: int = 0, attn: str = None) -> dict:
    """One open-loop run against a directly-instantiated replica callable
    (the serve path minus transport: scheduler + jitted programs — what
    the ISSUE-9/13 comparisons are about). mode: "continuous" | "batch";
    workload: "mixed" (ISSUE 9) | "prefix" (ISSUE 13 Zipf shared-prefix);
    kv_layout/prefix_cache select the paged arena + radix cache vs the
    PR-9 contiguous arena (continuous mode only); attn selects the paged
    attention lane (ISSUE 20: in-place "reference"/"pallas" vs the
    gathered-view "gather" baseline; None = the config default)."""
    from ray_tpu.serve.llm import LLMServerImpl

    kw = {}
    if mode == "continuous":
        kw = {"kv_layout": kv_layout,
              "prefix_cache": prefix_cache if kv_layout == "paged" else None}
        if kv_layout == "paged" and kv_pages:
            kw["kv_pages"] = kv_pages
        if kv_layout == "paged" and attn is not None:
            kw["attn"] = attn
    if workload == "prefix":
        # the shared preambles need a context window wider than the debug
        # preset's 128 (production few-shot preambles dwarf the tails);
        # every candidate gets the same window
        kw["preset_overrides"] = {"max_seq_len": max_seq_len}
    server = LLMServerImpl(
        preset=preset, max_new_tokens=new_tokens_cap, scheduler=mode,
        slots=slots, prefill_chunk=prefill_chunk, share_weights=False,
        max_batch_size=slots, **kw)
    try:
        if workload == "prefix":
            load = _make_prefix_load(seed, n, rate_rps, new_tokens_cap,
                                     prefix_len=prefix_len,
                                     max_seq_len=max_seq_len)
        else:
            load = _make_load(seed, n, rate_rps, new_tokens_cap)
        # warmup = a full replay of the SAME load, off the clock: the
        # request-level baseline compiles one program per (batch, length,
        # steps) shape its flushes happen to form — measuring its shape-
        # churn compiles would flatter the continuous path (which compiles
        # exactly two programs) for the wrong reason on CPU. For the
        # prefix-cache comparison the warm replay also PRE-POPULATES the
        # radix cache for both candidates symmetrically (the measured run
        # sees the steady-state hit rate, not the cold ramp)
        asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        warm = (server.scheduler_stats()
                if mode == "continuous" else {})
        out = asyncio.run(_drive_open_loop(
            server, load, streaming=(mode == "continuous")))
        out["scheduler"] = server.scheduler_stats()
        if mode == "continuous":
            st = out["scheduler"]
            # fallback guard: the ITERATION-LEVEL path must have engaged —
            # a silent fall-back to flush-and-drain cannot vacuously pass
            assert st["mode"] == "continuous", st
            assert st["admitted_mid_flight"] > 0, (
                "no request was admitted mid-generation; the open-loop "
                f"load never exercised continuous batching: {st}")
            assert st["kv_layout"] == kv_layout, st
            if prefix_cache and kv_layout == "paged":
                # fallback guard: the radix cache must actually have
                # spliced prefixes, and exactly two programs compiled
                assert st["prefix_hits"] > 0, (
                    f"prefix cache never hit on the shared-prefix load: "
                    f"{st}")
                assert st["compiled_programs"] == 2, st
                # steady-state hit rate: the MEASURED run's delta only
                # (the warmup replay exists precisely to absorb the
                # cold-ramp misses — don't blend them back in)
                dh = st["prefix_hits"] - warm.get("prefix_hits", 0)
                dm = st["prefix_misses"] - warm.get("prefix_misses", 0)
                out["prefix_hit_rate"] = round(dh / max(dh + dm, 1), 4)
        return out
    finally:
        server.shutdown()


# ----------------------------------------------------------------- fleet


def _fleet_arm(affinity: bool, load, *, replicas: int, slots: int,
               prefill_chunk: int, new_cap: int, max_seq_len: int,
               kv_pages: int, spec_k: int, skew: int, log) -> dict:
    """One fleet measurement: `replicas` copies of the LLM app through
    the REAL control plane (controller + router + replica actors), the
    Zipf shared-prefix load replayed open-loop from COLD caches. With
    ``affinity`` the router steers on prefix digests (fleet hits land on
    the holder; skew/fail fallbacks pull pages cross-replica); without it
    the same router runs affinity-blind pow-2 — the ISSUE-18 baseline."""
    import threading

    import ray_tpu
    import ray_tpu.serve as serve
    from ray_tpu._private import config as _conf_mod
    from ray_tpu.serve.llm import build_app

    os.environ["RAY_TPU_SERVE_AFFINITY"] = "1" if affinity else "0"
    # a tight skew bound matters under a Zipf head: overflow traffic must
    # fall back (and MIGRATE the prefix) instead of queueing on the
    # holder — that keeps p99 TTFT flat while the hit rate stays fleet-
    # wide (a migrated splice is still a prefix hit on the puller)
    os.environ["RAY_TPU_SERVE_AFFINITY_SKEW"] = str(skew)
    # the router reads the knobs at construction — refresh the cached
    # config so each arm's router sees its own settings
    _conf_mod._global_config = None
    name = "fleetaff" if affinity else "fleetblind"
    h = serve.run(build_app(num_replicas=replicas, max_new_tokens=new_cap,
                            slots=slots, prefill_chunk=prefill_chunk,
                            preset_overrides={"max_seq_len": max_seq_len},
                            kv_pages=kv_pages, drafter="self",
                            spec_k=spec_k),
                  name=name, route_prefix=f"/{name}")
    try:
        # compile every replica's programs off-meter (prefill + verify +
        # drafter); a lazily-compiling replica would pollute p99 TTFT
        # with multi-second compiles, asymmetrically between the arms
        h.remote({"prompt": "warmup"}).result(timeout=600)
        router = h._get_router()
        for rep in list(router._replicas):
            ray_tpu.get(rep.handle_request.remote(
                "__call__", ({"prompt": "warmup"},), {}), timeout=600)
        time.sleep(1.0)  # let the warmup digests propagate fleet-wide

        def rep_stats():
            out = []
            for rep in list(router._replicas):
                out.append(ray_tpu.get(rep.handle_request.remote(
                    "scheduler_stats", (), {}), timeout=60))
            return out

        st0 = rep_stats()
        sh = h.options(stream=True)
        lock = threading.Lock()
        results = []
        errors = [0]
        t_start = time.perf_counter()

        def one(at, prompt, budget):
            delay = t_start + at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            times = []
            try:
                for _chunk in sh.remote({"prompt": prompt, "stream": True,
                                         "max_new_tokens": budget}):
                    times.append(time.perf_counter())
            except Exception:
                with lock:
                    errors[0] += 1
                return
            with lock:
                results.append((t0, times))

        threads = [threading.Thread(target=one, args=req) for req in load]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        st1 = rep_stats()

        def agg(key):
            return sum(b.get(key, 0) - a.get(key, 0)
                       for a, b in zip(st0, st1))

        hits, misses = agg("prefix_hits"), agg("prefix_misses")
        drafted, accepted = agg("spec_drafted_tokens"), agg(
            "spec_accepted_tokens")
        rounds = agg("spec_rounds")
        emitted = sum(len(times) for _t0, times in results)
        ttfts = [times[0] - t0 for t0, times in results if times]
        wall = (max(t for _t0, ts in results for t in ts)
                - min(t0 for t0, _ts in results))
        out = {
            "affinity": affinity,
            "replicas": replicas,
            "requests_ok": len(results),
            "errors": errors[0],
            "wall_s": round(wall, 3),
            "tokens": emitted,
            "tokens_per_sec": round(emitted / wall, 1),
            "ttft_ms": _percentiles(ttfts),
            "fleet_prefix_hits": hits,
            "fleet_prefix_misses": misses,
            "fleet_hit_rate": round(hits / max(hits + misses, 1), 4),
            "migrations": agg("migrations"),
            "migrated_pages": agg("migrated_pages"),
            "migration_failures": agg("migration_failures"),
            "spec_drafted_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_decode_accept_rate": round(
                accepted / drafted, 4) if drafted else 0.0,
            "spec_tokens_per_step": round(
                sum(b.get("spec_tokens_per_step", 0.0) for b in st1)
                / max(sum(1 for b in st1
                          if b.get("spec_rounds", 0) > 0), 1), 3),
            "spec_rounds": rounds,
        }
        log(f"{name}: hit_rate={out['fleet_hit_rate']} "
            f"p99_ttft={out['ttft_ms']['p99']}ms "
            f"migrations={out['migrations']} "
            f"accept={out['spec_decode_accept_rate']}")
        return out
    finally:
        serve.shutdown()


def fleet_records(args, prov, log) -> list:
    """The ISSUE-18 fleet record pair: affinity-steered vs affinity-blind
    pow-2 over the same Zipf shared-prefix schedule, 4 replicas each."""
    import ray_tpu

    n = args.fleet_requests
    prefix_len = 192  # + tail + budget + spec reserve fits max_seq_len 256
    load = _make_prefix_load(args.seed, n, args.fleet_rate,
                             args.new_tokens_cap, prefix_len=prefix_len,
                             n_prefixes=args.fleet_prefixes,
                             max_seq_len=args.max_seq_len)
    from ray_tpu._private.config import global_config

    pt = global_config().serve_page_tokens
    pool = (args.slots * (args.max_seq_len // pt)
            + 8 * (prefix_len // pt) + 1)
    common = dict(replicas=args.fleet_replicas, slots=args.slots,
                  prefill_chunk=args.prefill_chunk,
                  new_cap=args.new_tokens_cap,
                  max_seq_len=args.max_seq_len, kv_pages=pool,
                  spec_k=args.spec_k, skew=args.fleet_skew, log=log)
    ray_tpu.init(num_cpus=max(8, 2 * args.fleet_replicas),
                 object_store_memory=512 * 1024 * 1024)
    try:
        log("fleet arm: affinity steering + migration + spec decode ...")
        aff = _fleet_arm(True, load, **common)
        log("fleet arm: affinity-blind pow-2 baseline ...")
        blind = _fleet_arm(False, load, **common)
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_SERVE_AFFINITY", None)
        os.environ.pop("RAY_TPU_SERVE_AFFINITY_SKEW", None)
        from ray_tpu._private import config as _conf_mod

        _conf_mod._global_config = None

    # the ISSUE-18 acceptance floor: steering must make prefix reuse a
    # FLEET property, not a per-replica accident
    assert aff["fleet_hit_rate"] >= 0.9, aff
    assert aff["errors"] == 0 and blind["errors"] == 0, (aff, blind)
    assert aff["spec_decode_accept_rate"] > 0, aff
    assert aff["spec_tokens_per_step"] > 1.0, aff
    detail = {"requests": n, "seed": args.seed,
              "rate_rps": args.fleet_rate, "slots": args.slots,
              "preset": args.preset, "prefix_len": prefix_len,
              "max_seq_len": args.max_seq_len, "spec_k": args.spec_k,
              "drafter": "self", "arrivals": "poisson",
              "workload": "prefix",
              "prefix_dist": (f"zipf(s=1.1) over {args.fleet_prefixes} x "
                              f"{prefix_len}-token preambles, "
                              f"4-10-token tails"),
              "measured_from": "cold caches (no warm replay): the ramp "
                               "IS the mechanism under test"}
    return [
        {"metric": "serve_fleet_affinity_hit_rate",
         "value": aff["fleet_hit_rate"], "unit": "fraction",
         "detail": {**aff, **detail, **prov}},
        {"metric": "serve_fleet_blind_hit_rate",
         "value": blind["fleet_hit_rate"], "unit": "fraction",
         "detail": {**blind, **detail, **prov}},
        {"metric": "serve_fleet_affinity_p99_ttft_ms",
         "value": aff["ttft_ms"]["p99"], "unit": "ms",
         "detail": {"vs_blind_p99_ttft_ms": blind["ttft_ms"]["p99"],
                    "vs_blind_p50_ttft_ms": blind["ttft_ms"]["p50"],
                    "affinity_p50_ttft_ms": aff["ttft_ms"]["p50"],
                    "migrations": aff["migrations"],
                    "migrated_pages": aff["migrated_pages"],
                    **detail, **prov}},
        {"metric": "serve_fleet_spec_decode_accept_rate",
         "value": aff["spec_decode_accept_rate"], "unit": "fraction",
         "detail": {"spec_tokens_per_step": aff["spec_tokens_per_step"],
                    "spec_drafted_tokens": aff["spec_drafted_tokens"],
                    "spec_accepted_tokens": aff["spec_accepted_tokens"],
                    "spec_rounds": aff["spec_rounds"],
                    **detail, **prov}},
    ]


def loadgen_main(args) -> None:
    log = lambda m: print(f"bench_serve: {m}", file=sys.stderr)  # noqa: E731
    prov = _probe_provenance(log)
    common = dict(slots=args.slots, new_tokens_cap=args.new_tokens_cap,
                  prefill_chunk=args.prefill_chunk,
                  prefix_len=args.prefix_len,
                  max_seq_len=args.max_seq_len)
    base_detail = {"requests": args.requests, "seed": args.seed,
                   "slots": args.slots, "preset": args.preset,
                   "new_tokens_cap": args.new_tokens_cap,
                   "arrivals": "poisson"}
    records = []

    # ---- ISSUE-13: Zipf shared-prefix workload, three-way ----
    # paged arena + radix prefix cache vs the PR-9 continuous arena vs
    # request-level batching, same offered load (saturating, so tokens/s
    # measures CAPACITY, not the arrival rate). The paged pool gets
    # headroom for the radix working set (the 8 preambles stay resident)
    # on top of the slots' demand — that residency IS the mechanism being
    # measured; the scheduler stats in the detail show what it held
    from ray_tpu._private.config import global_config

    pt = global_config().serve_page_tokens  # the scheduler's actual size
    pool = (args.slots * (args.max_seq_len // pt)
            + 8 * (args.prefix_len // pt) + 1)
    pfx_detail = {**base_detail, "workload": "prefix",
                  "rate_rps": args.prefix_rate,
                  "max_seq_len": args.max_seq_len,
                  "new_tokens_dist": "2+3*pareto(1.5), capped at 12",
                  "prefix_dist": (
                      f"zipf(s=1.1) over 8 x {args.prefix_len}-token "
                      f"preambles, 4-10-token tails")}
    log("paged+prefix continuous (zipf shared-prefix workload) ...")
    paged = run_loadgen("continuous", args.preset, args.prefix_rate,
                        args.requests, args.seed, workload="prefix",
                        kv_layout="paged", prefix_cache=True,
                        kv_pages=pool, attn=args.attn, **common)
    log("PR-9 contiguous continuous (zipf shared-prefix workload) ...")
    cont_p = run_loadgen("continuous", args.preset, args.prefix_rate,
                         args.requests, args.seed, workload="prefix",
                         kv_layout="contiguous", **common)
    log("request-level batch (zipf shared-prefix workload) ...")
    base_p = run_loadgen("batch", args.preset, args.prefix_rate,
                         args.requests, args.seed, workload="prefix",
                         **common)
    paged_speedup = paged["tokens_per_sec"] / max(
        cont_p["tokens_per_sec"], 1e-9)
    records += [
        {"metric": "serve_loadgen_paged_prefix_tokens_per_sec",
         "value": paged["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**paged, **pfx_detail, **prov}},
        {"metric": "serve_loadgen_continuous_prefix_tokens_per_sec",
         "value": cont_p["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**cont_p, **pfx_detail, **prov}},
        {"metric": "serve_loadgen_request_batch_prefix_tokens_per_sec",
         "value": base_p["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**base_p, **pfx_detail, **prov}},
        {"metric": "serve_paged_prefix_speedup",
         "value": round(paged_speedup, 2), "unit": "x",
         "detail": {"vs": "PR-9 contiguous continuous, same offered load",
                    "prefix_hit_rate": paged.get("prefix_hit_rate"),
                    # arena accounting, auditable from the record alone:
                    # the paged pool carries the radix working set ON TOP
                    # of the slots' demand — that residency is the
                    # mechanism being measured, not hidden headroom
                    "paged_pool_pages": paged["scheduler"]["num_pages"],
                    "paged_page_tokens":
                        paged["scheduler"]["page_tokens"],
                    "paged_peak_pages_in_use":
                        paged["scheduler"]["peak_pages_in_use"],
                    "contiguous_arena_tokens":
                        args.slots * args.max_seq_len,
                    "paged_p99_ttft_ms": paged["ttft_ms"]["p99"],
                    "continuous_p99_ttft_ms": cont_p["ttft_ms"]["p99"],
                    "paged_p50_ttft_ms": paged["ttft_ms"]["p50"],
                    "continuous_p50_ttft_ms": cont_p["ttft_ms"]["p50"],
                    **pfx_detail, **prov}},
    ]

    # ---- ISSUE-20: paged attention lane, in-place vs gathered-view ----
    # the SAME paged scheduler + radix cache + offered load, only the
    # attention lane differs: the in-place lane attends through the page
    # table, the gather baseline materializes every slot's provisioned
    # logical view per layer per step. attn_bytes_moved in the detail is
    # the audit trail — the gather arm's traffic tracks provisioning
    lane = paged["scheduler"]["attn_lane"]
    if lane != "gather":
        log("paged+prefix continuous, gathered-view attn lane "
            "(measured baseline) ...")
        paged_g = run_loadgen("continuous", args.preset, args.prefix_rate,
                              args.requests, args.seed, workload="prefix",
                              kv_layout="paged", prefix_cache=True,
                              kv_pages=pool, attn="gather", **common)
        assert paged_g["scheduler"]["attn_lane"] == "gather", (
            "gather arm resolved the wrong lane")
        lane_speedup = paged["tokens_per_sec"] / max(
            paged_g["tokens_per_sec"], 1e-9)
        records += [
            {"metric": "serve_loadgen_paged_gather_tokens_per_sec",
             "value": paged_g["tokens_per_sec"], "unit": "tokens/s",
             "detail": {**paged_g, **pfx_detail, **prov}},
            {"metric": "serve_paged_attn_lane_speedup",
             "value": round(lane_speedup, 2), "unit": "x",
             "detail": {"vs": "gathered-view lane, same paged scheduler "
                              "and offered load",
                        "attn_lane": lane,
                        "inplace_attn_bytes_moved":
                            paged["scheduler"]["attn_bytes_moved"],
                        "gather_attn_bytes_moved":
                            paged_g["scheduler"]["attn_bytes_moved"],
                        "inplace_p99_ttft_ms": paged["ttft_ms"]["p99"],
                        "gather_p99_ttft_ms": paged_g["ttft_ms"]["p99"],
                        **pfx_detail, **prov}},
        ]

    # ---- ISSUE-9 continuity: mixed workload, continuous vs batch ----
    # (the PR-9 record, re-measured on the PR-9 contiguous arena: the
    # mixed-length heavy-tail load where iteration-level scheduling wins;
    # uniform near-window-length prompts would instead flatter the
    # whole-prompt-prefill batch path)
    mix_detail = {**base_detail, "workload": "mixed",
                  "rate_rps": args.rate,
                  "new_tokens_dist": "1+4*pareto(1.5), capped"}
    log("PR-9 contiguous continuous (mixed workload) ...")
    cont = run_loadgen("continuous", args.preset, args.rate, args.requests,
                       args.seed, workload="mixed",
                       kv_layout="contiguous", **common)
    log("request-level batch baseline (mixed workload) ...")
    base = run_loadgen("batch", args.preset, args.rate, args.requests,
                       args.seed, workload="mixed", **common)
    speedup = cont["tokens_per_sec"] / max(base["tokens_per_sec"], 1e-9)
    ttft_ratio = (base["ttft_ms"]["p99"] or 0.0) / max(
        cont["ttft_ms"]["p99"] or 1e-9, 1e-9)
    records += [
        {"metric": "serve_loadgen_continuous_tokens_per_sec",
         "value": cont["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**cont, **mix_detail, **prov}},
        {"metric": "serve_loadgen_request_batch_tokens_per_sec",
         "value": base["tokens_per_sec"], "unit": "tokens/s",
         "detail": {**base, **mix_detail, **prov}},
        {"metric": "serve_continuous_speedup",
         "value": round(speedup, 2), "unit": "x",
         "detail": {"p99_ttft_improvement_x": round(ttft_ratio, 2),
                    "continuous_p99_ttft_ms": cont["ttft_ms"]["p99"],
                    "baseline_p99_ttft_ms": base["ttft_ms"]["p99"],
                    "continuous_p50_ttft_ms": cont["ttft_ms"]["p50"],
                    "baseline_p50_ttft_ms": base["ttft_ms"]["p50"],
                    **mix_detail, **prov}},
    ]
    if args.fleet:
        records += fleet_records(args, prov, log)
    for rec in records:
        print(json.dumps(rec))
    if args.json_out:
        _write_doc(records, prov, args.json_out)


def _write_doc(records, prov, path) -> None:
    doc = {
        "suite": "serve_llm_continuous_batching",
        "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
        "host": __import__("platform").platform(),
        "provenance": prov,
        "records": records,
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt2_small")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--serve", action="store_true",
                    help="drive the full Serve deployment (continuous "
                         "batching) instead of the raw decode program")
    ap.add_argument("--loadgen", action="store_true",
                    help="open-loop load generator: continuous vs "
                         "request-level batching at the same offered load")
    ap.add_argument("--fleet", action="store_true",
                    help="ISSUE-18 fleet arms: 4 replicas through the real "
                         "control plane, prefix-affinity steering + page "
                         "migration + speculative decoding vs affinity-"
                         "blind pow-2, same Zipf shared-prefix schedule")
    ap.add_argument("--fleet-replicas", type=int, default=4)
    ap.add_argument("--fleet-rate", type=float, default=8.0,
                    help="fleet-arm Poisson arrival rate (req/s); fast "
                         "enough that the blind arm's cold prefills queue "
                         "(the contrast under test) while digest "
                         "propagation (0.5s reconcile) still keeps up")
    ap.add_argument("--fleet-requests", type=int, default=320)
    ap.add_argument("--fleet-prefixes", type=int, default=8,
                    help="distinct Zipf preambles in the fleet schedule; "
                         "affinity pins each to one holder, pow-2 "
                         "scatters them across the fleet")
    ap.add_argument("--fleet-skew", type=int, default=4,
                    help="affinity load-skew bound for the fleet arms "
                         "(holder inflight may exceed the min by this "
                         "much before steering falls back + migrates)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculative round (fleet arms)")
    ap.add_argument("--rate", type=float, default=75.0,
                    help="mixed-workload Poisson arrival rate (req/s); the "
                         "default saturates the request-level baseline "
                         "on a CPU host so the capacity gap is visible")
    ap.add_argument("--prefix-rate", type=float, default=600.0,
                    help="shared-prefix-workload arrival rate (req/s); "
                         "must saturate BOTH continuous schedulers so "
                         "tokens/s measures capacity, not arrivals")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--new-tokens-cap", type=int, default=48)
    ap.add_argument("--prefill-chunk", type=int, default=16,
                    help="scheduler prefill chunk width (both continuous "
                         "candidates)")
    ap.add_argument("--prefix-len", type=int, default=224,
                    help="shared preamble length (tokens) for the prefix "
                         "workload")
    ap.add_argument("--max-seq-len", type=int, default=256,
                    help="context-window override for the prefix workload "
                         "(preamble + tail + budget must fit)")
    ap.add_argument("--attn", default=None,
                    choices=["auto", "pallas", "reference", "gather"],
                    help="paged attention lane for the paged loadgen arm "
                         "(default: the RAY_TPU_SERVE_PAGED_ATTN config "
                         "default); when it resolves in-place, a gather-"
                         "lane arm runs too for the ISSUE-20 comparison")
    ap.add_argument("--json-out", default="",
                    help="also write the full loadgen suite to this file")
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--requests", type=int, default=None,
                    help="total requests (default: 150 loadgen, 64 serve)")
    args = ap.parse_args(argv)
    if args.requests is None:
        args.requests = 150 if args.loadgen else 64

    if args.loadgen or args.fleet:
        if args.preset == "gpt2_small":
            args.preset = "llama_debug"  # loadgen default: runnable anywhere
        if not args.loadgen:
            # fleet-only invocation: skip the single-replica loadgen arms
            log = lambda m: print(  # noqa: E731
                f"bench_serve: {m}", file=sys.stderr)
            prov = _probe_provenance(log)
            records = fleet_records(args, prov, log)
            for rec in records:
                print(json.dumps(rec))
            if args.json_out:
                _write_doc(records, prov, args.json_out)
            return
        loadgen_main(args)
        return

    if args.serve:
        detail = bench_serve_path(args.preset, args.new_tokens,
                                  args.concurrency, args.requests)
        print(json.dumps({
            "metric": "serve_llm_decode_tokens_per_sec",
            "value": detail["serve_decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": round(
                detail["serve_decode_tokens_per_sec"] / 1000.0, 4),
            "detail": dict(detail, preset=args.preset,
                           new_tokens=args.new_tokens),
        }))
        return

    import jax

    detail = bench_decode(args.preset, args.prompt_len, args.new_tokens)
    best = max(detail["per_batch"],
               key=lambda r: r["decode_tokens_per_sec"])
    print(json.dumps({
        "metric": "llm_decode_tokens_per_sec",
        "value": best["decode_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": round(best["decode_tokens_per_sec"] / 1000.0, 4),
        "detail": dict(detail,
                       device=str(getattr(jax.devices()[0], "device_kind",
                                          "cpu"))),
    }))


if __name__ == "__main__":
    main()
